"""Performance-observatory engine integration: token parity with the
TSDB + roofline + CUSUM detector on, the seeded ``slow_program`` drill
firing within budget and blaming the stalled phase, the ``/timeseries``
and ``/graphz`` introspection endpoints, and the bounded-eviction
contracts of the admission rejection ring and the trace sampler.

The parity invariant is the headline (same bar as every other
observability layer in this repo): the observatory may time, bucket and
test every step, but it must never change a greedy token. The drill
mirrors ``bench.py --perfwatch`` / ``tools/serving_smoke.sh perfwatch``
at unit scale — and, like them, warms the decode stratum BEFORE arming
the stall: a stratum first seen mid-stall anchors its median/MAD
baseline on stalled samples and honestly reports "normal".
All on CPU (conftest pins JAX_PLATFORMS=cpu).
"""

import json
import os
import urllib.error

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu import chaos
from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.obs.disttrace import TraceSampler
from distributed_pytorch_tpu.obs.server import scrape
from distributed_pytorch_tpu.obs.timeseries import TimeSeriesDB
from distributed_pytorch_tpu.serving import (
    AdmissionController,
    InferenceEngine,
    RequestTooLong,
    SamplingParams,
)

VOCAB = 48


def tiny_lm():
    return TransformerLM(
        vocab_size=VOCAB, d_model=16, n_layers=1, n_heads=2, d_ff=32,
        dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def model_and_params():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


ENGINE_KW = dict(
    max_slots=4, max_seq_len=32, page_size=4, token_budget=32,
    max_prefill_chunk=8, debug=True,
)

PROMPTS = [[5, 7, 11, 2, 1], [6, 1, 9], [40, 41, 3], [3, 3, 3, 3, 8]]


def make_engine(model, params, **kw):
    opts = dict(ENGINE_KW)
    opts.update(kw)
    return InferenceEngine(model, params, **opts)


def run_batch(eng, max_new=8):
    ids = [
        eng.submit(p, SamplingParams(max_new_tokens=max_new))
        for p in PROMPTS
    ]
    eng.run()
    return [list(eng.requests[i].generated) for i in ids]


def _disarm():
    os.environ.pop(chaos.ENV_VAR, None)
    chaos._reset()


# ------------------------------------------------------------------ parity


class TestObservatoryParity:
    def test_tokens_bitwise_identical_with_observatory_on(
        self, model_and_params
    ):
        model, params = model_and_params
        eng_off = make_engine(model, params)
        ref = run_batch(eng_off)
        eng_off.close()

        eng = make_engine(model, params, timeseries=True, xla_ledger=True)
        assert run_batch(eng) == ref
        # ...and every subsystem actually observed the run.
        st = eng.timeseries.status()
        assert st["series"] > 0 and st["samples_taken"] > 0
        assert eng.regress.steps > 0
        assert eng.roofline is not None
        rep = eng.roofline.report()
        assert rep["programs"], "roofline saw no ledger programs"
        eng.close()

    def test_observatory_off_by_default(self, model_and_params):
        model, params = model_and_params
        eng = make_engine(model, params)
        assert eng.timeseries is None
        assert eng.regress is None
        assert eng.roofline is None
        eng.close()

    def test_engine_accepts_injected_db(self, model_and_params):
        model, params = model_and_params
        db = TimeSeriesDB(raw_capacity=16)
        eng = make_engine(model, params, timeseries=db)
        assert eng.timeseries is db
        run_batch(eng)
        assert db.status()["samples_taken"] > 0
        eng.close()


# ------------------------------------------------------------------- drill


class TestRegressionDrill:
    def test_seeded_stall_fires_within_budget_blaming_phase(
        self, model_and_params
    ):
        """Clean pass warms the decode strata and must end quiet; the
        armed pass stalls ``dispatch`` persistently and the detector must
        fire within the sample budget, blame dispatch, and the stall must
        not change a single token (a sleep is not a sample)."""
        model, params = model_and_params
        eng = make_engine(model, params, timeseries=True)
        _disarm()
        try:
            ref = run_batch(eng, max_new=12)
            assert eng.regress.alerts == 0, eng.regress.events

            os.environ[chaos.ENV_VAR] = json.dumps({
                "faults": [{
                    "kind": "slow_program",
                    "phase": "dispatch",
                    "duration": 0.05,
                    "at_step": 3,
                }],
            })
            chaos._reset()  # re-arm from the env (also clears observers)
            injected = {}

            def observer(kind, step, mode):
                if kind == "slow_program" and "regress_step" not in injected:
                    injected["regress_step"] = eng.regress.steps + 1

            chaos.add_fault_observer(observer)
            try:
                assert run_batch(eng, max_new=12) == ref
            finally:
                chaos.remove_fault_observer(observer)
        finally:
            _disarm()
            eng.close()

        assert eng.regress.alerts >= 1
        event = eng.regress.events[-1]
        assert event["attributed_phase"] == "dispatch"
        assert eng.regress.last_attribution == "dispatch"
        # Latency in raw detector steps from the first stalled step; the
        # warm stratum needs only the CUSUM crossing (2 ticks at the
        # default clip/h), slack for prefill-mixed steps at batch start.
        latency = event["step"] - injected["regress_step"] + 1
        assert 1 <= latency <= 10, (latency, event)
        assert event["stratum_samples"] > 0

    def test_acknowledge_clears_firing(self, model_and_params):
        model, params = model_and_params
        eng = make_engine(model, params, timeseries=True)
        _disarm()
        try:
            run_batch(eng, max_new=12)
            os.environ[chaos.ENV_VAR] = json.dumps({
                "faults": [{
                    "kind": "slow_program",
                    "phase": "schedule",
                    "duration": 0.05,
                    "at_step": 2,
                }],
            })
            chaos._reset()
            run_batch(eng, max_new=12)
        finally:
            _disarm()
        assert eng.regress.firing
        eng.regress.acknowledge()
        assert not eng.regress.firing
        assert eng.regress.alerts >= 1  # history survives the ack
        eng.close()


# --------------------------------------------------------------- endpoints


class TestTimeseriesEndpoints:
    @pytest.fixture(scope="class")
    def served(self, model_and_params):
        model, params = model_and_params
        eng = make_engine(model, params, timeseries=True, xla_ledger=True)
        run_batch(eng)
        server = eng.serve()
        yield eng, server
        eng.close()

    def test_timeseries_json_and_filter(self, served):
        _eng, server = served
        doc = scrape(server.url, "/timeseries")
        assert doc["series"], "empty TSDB dump"
        name = sorted(doc["series"])[0]
        one = scrape(server.url, f"/timeseries?series={name}")
        assert set(one["series"]) == {name}
        assert one["series"][name]["points"], "selected series has no points"

    def test_graphz_sparklines(self, served):
        _eng, server = served
        html = scrape(server.url, "/graphz")
        assert isinstance(html, str)
        assert "performance observatory" in html
        assert any(c in html for c in "▁▂▃▄▅▆▇█")

    def test_404_without_tsdb(self, model_and_params):
        model, params = model_and_params
        eng = make_engine(model, params)
        run_batch(eng)
        server = eng.serve()
        try:
            with pytest.raises(urllib.error.HTTPError):
                scrape(server.url, "/timeseries")
            with pytest.raises(urllib.error.HTTPError):
                scrape(server.url, "/graphz")
        finally:
            eng.close()


# ---------------------------------------------------- bounded-ring satellites


class TestRejectionRingEviction:
    def test_ring_evicts_oldest_at_configured_bound(self):
        adm = AdmissionController(
            max_queue=4, max_request_tokens=16, recent_rejections_max=4
        )
        for i in range(6):
            with pytest.raises(RequestTooLong):
                adm.check(
                    prompt_len=100,
                    params=SamplingParams(max_new_tokens=1),
                    queue_len=0,
                    trace_id=f"t{i}",
                )
        ring = list(adm.recent_rejections)
        assert len(ring) == 4  # storm cost is O(max), never O(rejections)
        assert [r["trace_id"] for r in ring] == ["t2", "t3", "t4", "t5"]
        assert adm.rejected_too_long == 6  # counters keep the true total

    def test_default_bound_and_validation(self):
        adm = AdmissionController(max_queue=4, max_request_tokens=16)
        assert adm.recent_rejections.maxlen == 32
        with pytest.raises(ValueError):
            AdmissionController(
                max_queue=4, max_request_tokens=16, recent_rejections_max=0
            )

    def test_trace_sampler_shares_the_eviction_contract(self):
        smp = TraceSampler(head_rate=1.0, max_kept=2)
        for t in ("t1", "t2", "t3"):
            assert smp.note_end(t)
        assert smp.kept_ids() == ["t2", "t3"]
        assert smp.evicted == 1
        assert "t1" in smp.drain_drops()  # evictee queued for pruning
