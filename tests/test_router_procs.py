"""Durable-control-plane drills against REAL processes.

The headline: a ROUTER process is SIGKILLed mid-decode over three live
worker subprocesses under seeded Poisson load. The workers — spawned
with ``TPURUN_ORPHAN_GRACE`` — notice the parent's death (stdin EOF),
freeze in the orphan state instead of dying, and a SECOND router built
by ``FleetRouter.recover`` in the test process re-adopts them from the
write-ahead journal plus the worker registry. Acceptance: union greedy
token parity with an uninterrupted single-engine reference, zero
duplicate or missing tokens, zero page leaks on every worker, the
orphan state machine visible in the worker flight recorder, and trace
ids minted by the dead router threading through the recovered one.

Also here: the orphan-grace suicide deadline (an unclaimed orphan still
dies, exit 3, just late enough for re-adoption to win the race) and the
``/adopt`` identity guard (PID reuse / wrong-name claims are refused
with 409).

All slow (each spawns JAX subprocesses); the fleet-chaos CI job runs
them alongside ``tools/fleet_smoke.sh router``.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu import chaos
from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.obs import Tracer
from distributed_pytorch_tpu.serving import (
    FleetRouter,
    InferenceEngine,
    ProcessReplicaClient,
    SamplingParams,
    pid_alive,
    read_worker_registry,
)

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

MODEL_KW = dict(
    vocab_size=48, d_model=16, n_layers=1, n_heads=2, d_ff=32,
)
ENGINE_KW = dict(
    max_slots=2, max_seq_len=32, page_size=4, token_budget=16,
    max_prefill_chunk=8, debug=True,
)
MAX_NEW = 6

PREFIX = [5, 7, 11, 2]
AFFINITY_PROMPTS = [PREFIX + [t, t + 1] for t in (1, 9, 17, 25, 33)]
OTHER_PROMPTS = [[2, 2, 3, 17, 40], [6, 1, 9], [40, 41], [3, 3, 3, 3, 8]]
DRILL_PROMPTS = AFFINITY_PROMPTS + OTHER_PROMPTS


def worker_spec(name, **extra):
    spec = {
        "name": name,
        "model": dict(MODEL_KW, dtype="float32"),
        "init_seed": 0,
        "engine": ENGINE_KW,
        "trace": True,
        "trace_every": 1,
        # Large enough that post-recovery decode traffic (step/admit
        # events) cannot push the orphan_enter/orphan_exit marks out of
        # the bounded ring before the drill inspects /postmortem.
        "flight": {"capacity": 8192},
    }
    spec.update(extra)
    return spec


def params_for(i):
    return SamplingParams(max_new_tokens=MAX_NEW)


@pytest.fixture(autouse=True)
def _fresh_chaos_plan():
    chaos._reset()
    yield
    os.environ.pop(chaos.ENV_VAR, None)
    chaos._reset()


@pytest.fixture(scope="module")
def ref_outputs():
    model = TransformerLM(**MODEL_KW, dtype=jnp.float32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    eng = InferenceEngine(model, params, **ENGINE_KW)
    ids = [
        eng.submit(p, params_for(i)) for i, p in enumerate(DRILL_PROMPTS)
    ]
    eng.run()
    out = {i: eng.poll(rid).generated for i, rid in enumerate(ids)}
    eng.close()
    return out


# The incarnation-1 router. It arms a hard-mode ``kill_router`` fault —
# a REAL SIGKILL of its own process at a step boundary — spawns three
# registry-tracked workers with an orphan-grace window, journals into
# the run dir, and pumps seeded Poisson load until the fault lands.
DRIVER = """
import json, os, random, sys

jdir = sys.argv[1]
cfg = json.load(open(os.path.join(jdir, "drill_cfg.json")))

from distributed_pytorch_tpu import chaos

os.environ[chaos.ENV_VAR] = json.dumps({
    "seed": 1234,
    "faults": [{"kind": "kill_router", "at_step": cfg["kill_step"]}],
})
chaos._reset()

from distributed_pytorch_tpu.serving import (
    FleetRouter, SamplingParams, spawn_replica_clients,
)

env = dict(os.environ)
env["TPURUN_ORPHAN_GRACE"] = str(cfg["orphan_grace_s"])
clients = spawn_replica_clients(cfg["specs"], run_dir=jdir, env=env)
router = FleetRouter(clients, journal_dir=jdir)

rng = random.Random(1234)
schedule = {}
rnd = 0
for idx in range(len(cfg["prompts"])):
    schedule.setdefault(rnd, []).append(idx)
    while rng.random() < 0.5:
        rnd += 1

fids = {}
rounds = 0
while True:
    for idx in schedule.pop(rounds, []):
        fids[idx] = router.submit(
            cfg["prompts"][idx],
            SamplingParams(max_new_tokens=cfg["max_new"]),
        )
        tmp = os.path.join(jdir, "fids.json.tmp")
        with open(tmp, "w") as f:
            json.dump(fids, f)
        os.replace(tmp, os.path.join(jdir, "fids.json"))
    router.step()  # the armed kill_router SIGKILLs this process here
    rounds += 1
    if rounds > 200:
        print("kill_router never fired", flush=True)
        sys.exit(1)
"""


def test_router_sigkill_recovery_drill(tmp_path, ref_outputs):
    """The headline drill: SIGKILL the router process mid-decode over 3
    live workers, recover in THIS process, re-adopt all three, finish
    everything with union parity and no leaks."""
    jdir = str(tmp_path)
    cfg = {
        "specs": [worker_spec(f"r{i}") for i in range(3)],
        "prompts": DRILL_PROMPTS,
        "max_new": MAX_NEW,
        "kill_step": 4,
        "orphan_grace_s": 300,
    }
    json.dump(cfg, open(os.path.join(jdir, "drill_cfg.json"), "w"))
    driver = os.path.join(jdir, "driver.py")
    open(driver, "w").write(DRIVER)

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env.pop(chaos.ENV_VAR, None)
    proc = subprocess.run(
        [sys.executable, driver, jdir],
        capture_output=True, text=True, timeout=570, env=env,
    )
    # The kill was real: the router died by SIGKILL, not sys.exit.
    assert proc.returncode == -9, (
        f"driver exited {proc.returncode}:\n{proc.stdout}\n{proc.stderr}"
    )
    fids = {
        int(k): int(v)
        for k, v in json.load(
            open(os.path.join(jdir, "fids.json"))
        ).items()
    }
    assert fids, "kill landed before any submit"

    registry = read_worker_registry(jdir)
    assert sorted(registry) == ["r0", "r1", "r2"]
    for entry in registry.values():
        assert pid_alive(entry["pid"]), (
            "worker died with the router despite the orphan grace"
        )
    time.sleep(1.0)  # let every worker notice the EOF, enter orphan state

    recovered = FleetRouter.recover(jdir, tracer=Tracer())
    try:
        summary = recovered.last_recovery
        assert sorted(summary["re_adopted_workers"]) == ["r0", "r1", "r2"]
        assert summary["lost_workers"] == []
        assert summary["lost"] == 0
        for rep in recovered.replicas():
            assert rep.client.adopted
            assert rep.client.adopted_orphan, (
                f"{rep.name} was claimed but never saw the orphan state"
            )

        # Clients whose submits the dead router never admitted retry
        # against the restarted one; journaled fids are never reissued.
        for idx in range(len(DRILL_PROMPTS)):
            if idx not in fids:
                new_fid = recovered.submit(
                    DRILL_PROMPTS[idx], params_for(idx)
                )
                assert new_fid not in fids.values()
                fids[idx] = new_fid
        rounds = 0
        while not all(
            s.finished for s in recovered._shadows.values()
        ):
            recovered.step()
            rounds += 1
            assert rounds < 500, "post-recovery drill did not converge"

        # Union parity: every prompt, across both incarnations.
        for idx, fid in fids.items():
            st = recovered.poll(fid)
            assert st.finished, f"prompt {idx} never finished"
            assert list(st.generated) == list(ref_outputs[idx]), (
                f"prompt {idx}: fleet produced {st.generated}, "
                f"reference {ref_outputs[idx]}"
            )
        # Zero page leaks on every re-adopted worker.
        for rep in recovered.replicas():
            assert rep.client.read_gauge("pages_referenced") == 0, (
                f"{rep.name} leaked referenced pages"
            )

        # The orphan state machine left its marks in the worker flight
        # recorder: enter on EOF, exit on /adopt.
        with urllib.request.urlopen(
            recovered.replicas()[0].client.obs_url + "/postmortem",
            timeout=10,
        ) as resp:
            doc = json.loads(resp.read().decode("utf-8"))
        kinds = [e["kind"] for e in doc["events"]]
        assert "orphan_enter" in kinds
        assert "orphan_exit" in kinds
        exit_ev = next(
            e for e in doc["events"] if e["kind"] == "orphan_exit"
        )
        assert exit_ev["adopted"] is True

        # Incarnation-1 trace ids thread through incarnation 2: the
        # recovery re-opened router spans under the journaled ids.
        trace = recovered.tracer.to_perfetto()
        recovered_spans = [
            e
            for e in trace["traceEvents"]
            if e.get("ph") == "b"
            and e.get("args", {}).get("routed_by") == "recovered"
        ]
        assert recovered_spans, "recovery opened no router spans"
        shadow_tids = {
            s.trace_id for s in recovered._shadows.values()
        }
        for ev in recovered_spans:
            assert ev["args"]["trace_id"] in shadow_tids

        # The recovery dump is on disk next to the journal (a CI
        # artifact in the smoke drill) when a flight recorder rides the
        # recovered router; the reconciliation summary is journaled.
        assert summary == recovered.describe()["recovery"]
    finally:
        recovered.close()

    # Clean close through the ATTACHED clients: every worker got the
    # polite /shutdown and actually exited.
    for name, entry in registry.items():
        deadline = time.time() + 15
        while pid_alive(entry["pid"]) and time.time() < deadline:
            time.sleep(0.1)
        assert not pid_alive(entry["pid"]), f"{name} still running"


def test_orphan_grace_suicide_without_adoption():
    """An unclaimed orphan still dies — exit 3, same as the default
    die-on-EOF, just delayed by the grace window."""
    env = dict(os.environ)
    env["TPURUN_ORPHAN_GRACE"] = "1.5"
    client = ProcessReplicaClient(worker_spec("lone"), env=env)
    try:
        t0 = time.monotonic()
        client._proc.stdin.close()  # the "router" dies
        code = client._proc.wait(30)
        elapsed = time.monotonic() - t0
        assert code == 3
        assert elapsed >= 1.0, "suicide fired before the grace elapsed"
    finally:
        client.abandon()


def test_orphan_default_dies_immediately():
    """Without the grace env the EOF contract is unchanged: immediate
    exit 3 (no drill can leak an orphan worker by accident)."""
    env = dict(os.environ)
    env.pop("TPURUN_ORPHAN_GRACE", None)
    client = ProcessReplicaClient(worker_spec("nograce"), env=env)
    try:
        client._proc.stdin.close()
        assert client._proc.wait(15) == 3
    finally:
        client.abandon()


def test_adopt_identity_guard_and_resume(tmp_path):
    """``/adopt`` is the PID-reuse guard: a claim with the wrong name is
    refused 409; the rightful claim succeeds, un-freezes the worker, and
    decode resumes over the new client."""
    run = str(tmp_path)
    env = dict(os.environ)
    env["TPURUN_ORPHAN_GRACE"] = "300"
    spawner = ProcessReplicaClient(worker_spec("r0"), env=env, run_dir=run)
    adopted = None
    try:
        entry = read_worker_registry(run)["r0"]
        spawner._proc.stdin.close()  # orphan it
        time.sleep(0.5)

        imposter = dict(entry, name="imposter")
        with pytest.raises(ValueError):
            ProcessReplicaClient.attach(imposter, run_dir=run)

        adopted = ProcessReplicaClient.attach(entry, run_dir=run)
        assert adopted.adopted and adopted.adopted_orphan
        # The worker is live again under the new client: submit + step.
        rid = adopted.submit([1, 2, 3], SamplingParams(max_new_tokens=2))
        done = set()
        for _ in range(100):
            done.update(adopted.step())
            if rid in done:
                break
        assert rid in done
        # Re-adoption is idempotent (a retried claim converges) and the
        # second claim reports the worker is NOT orphaned anymore.
        again = ProcessReplicaClient.attach(entry, run_dir=run)
        assert again.adopted and not again.adopted_orphan

        adopted.close()  # polite /shutdown over the attached client
        # The spawning parent can still reap: clean exit, leak asserts
        # passed INSIDE the worker.
        assert spawner._proc.wait(15) == 0
        # Deliberate teardown removed the registry entry.
        assert "r0" not in read_worker_registry(run)
        adopted = None
    finally:
        if adopted is not None:
            adopted.abandon()
        spawner.abandon()
