"""Evaluation-path tests: forward-only loss, BatchNorm eval mode, sharding."""

import jax
import numpy as np
import optax
import pytest

from distributed_pytorch_tpu.models import ResNet18, ToyRegressor
from distributed_pytorch_tpu.parallel.mesh import make_mesh
from distributed_pytorch_tpu.training.losses import (
    mse_loss,
    softmax_cross_entropy_loss,
)
from distributed_pytorch_tpu.training.trainer import Trainer
from distributed_pytorch_tpu.utils.data import MaterializedDataset, ShardedLoader


def test_evaluate_matches_train_loss_for_stateless_model():
    """For a stateless model with frozen params, eval loss on the training
    data equals the loss the next train step reports (pre-update)."""
    data = MaterializedDataset(64)
    loader = ShardedLoader(data, 64)
    trainer = Trainer(ToyRegressor(), loader, optax.sgd(0.0), save_every=0,
                      loss_fn=mse_loss)
    eval_loss = trainer.evaluate(ShardedLoader(data, 64))
    (xs, ys) = next(iter(loader))
    _, train_loss = trainer.train_step(trainer.state, trainer._put_batch(xs, ys))
    np.testing.assert_allclose(eval_loss, float(train_loss), rtol=1e-6)


def test_evaluate_does_not_mutate_state():
    data = MaterializedDataset(32)
    trainer = Trainer(ToyRegressor(), ShardedLoader(data, 32), optax.sgd(1e-3),
                      save_every=0, loss_fn=mse_loss)
    before = jax.tree_util.tree_map(np.asarray, trainer.state.params)
    step_before = int(trainer.state.step)
    trainer.evaluate(ShardedLoader(data, 16))
    after = jax.tree_util.tree_map(np.asarray, trainer.state.params)
    jax.tree_util.tree_map(np.testing.assert_array_equal, before, after)
    assert int(trainer.state.step) == step_before


@pytest.mark.slow
def test_evaluate_batchnorm_uses_running_stats():
    """ResNet eval must run with use_running_average=True: identical inputs in
    different batch compositions give identical per-sample outputs (train-mode
    BN would normalize by the batch's own stats and differ)."""
    rng = np.random.default_rng(0)

    class TinyImages:
        def __init__(self):
            self.inputs = rng.standard_normal((16, 8, 8, 3)).astype(np.float32)
            self.targets = rng.integers(0, 4, (16,)).astype(np.int32)

        def __len__(self):
            return 16

        def __getitem__(self, i):
            return self.inputs[i], self.targets[i]

    data = TinyImages()
    trainer = Trainer(
        ResNet18(num_classes=4), ShardedLoader(data, 8), optax.sgd(1e-2),
        save_every=0, loss_fn=softmax_cross_entropy_loss,
    )
    trainer.train(1)  # accumulate some running stats
    variables = {"params": trainer.state.params, **trainer.state.model_state}
    full = trainer._eval_apply(variables, data.inputs)
    halves = np.concatenate([
        np.asarray(trainer._eval_apply(variables, data.inputs[:8])),
        np.asarray(trainer._eval_apply(variables, data.inputs[8:])),
    ])
    np.testing.assert_allclose(np.asarray(full), halves, atol=1e-5)


@pytest.mark.slow
def test_evaluate_includes_moe_aux_loss():
    """Eval loss must include sown penalty terms, matching the train-step
    loss definition (frozen params + same batch => identical numbers)."""
    from distributed_pytorch_tpu.models import TransformerLM

    rng = np.random.default_rng(2)

    class Tokens:
        def __init__(self):
            toks = rng.integers(0, 32, (8, 17), dtype=np.int32)
            self.inputs, self.targets = toks[:, :-1], toks[:, 1:]

        def __len__(self):
            return 8

        def __getitem__(self, i):
            return self.inputs[i], self.targets[i]

    data = Tokens()
    model = TransformerLM(
        vocab_size=32, d_model=16, n_layers=2, n_heads=2, d_ff=32,
        n_experts=2, moe_every=2,
    )
    trainer = Trainer(
        model, ShardedLoader(data, 8), optax.sgd(0.0), save_every=0,
        loss_fn=softmax_cross_entropy_loss,
    )
    eval_loss = trainer.evaluate(ShardedLoader(data, 8))
    xs, ys = next(iter(ShardedLoader(data, 8)))
    _, train_loss = trainer.train_step(trainer.state, trainer._put_batch(xs, ys))
    np.testing.assert_allclose(eval_loss, float(train_loss), rtol=1e-6)


def test_evaluate_sharded():
    data = MaterializedDataset(96)
    mesh = make_mesh({"data": 8})
    trainer = Trainer(ToyRegressor(), ShardedLoader(data, 32), optax.sgd(1e-3),
                      save_every=0, mesh=mesh, loss_fn=mse_loss)
    serial = Trainer(ToyRegressor(), ShardedLoader(data, 32), optax.sgd(1e-3),
                     save_every=0, loss_fn=mse_loss)
    sharded_loss = trainer.evaluate(ShardedLoader(data, 32))
    serial_loss = serial.evaluate(ShardedLoader(data, 32))
    np.testing.assert_allclose(sharded_loss, serial_loss, rtol=1e-6)


# ----------------------------------------------------- exact (weighted) eval


class TestExactEval:
    """Trainer.evaluate with per-sample metrics: wrap-pad duplicates carry
    weight zero, so eval means are exact on ANY dataset size / mesh shape —
    closing the round-2 'wrap-pad bias is documented, not solved' item."""

    def _trainer(self, mesh=None):
        import jax.numpy as jnp
        import optax

        from distributed_pytorch_tpu import ShardedLoader, Trainer
        from distributed_pytorch_tpu.models import ToyRegressor
        from distributed_pytorch_tpu.training.losses import mse_loss
        from distributed_pytorch_tpu.utils.data import MaterializedDataset

        dataset = MaterializedDataset(64)
        loader = ShardedLoader(dataset, 16)
        return Trainer(
            ToyRegressor(), loader, optax.sgd(1e-3), 0,
            mesh=mesh, loss_fn=mse_loss,
        )

    def _exact_mse(self, trainer, dataset):
        """Handmade distinct-sample mean loss, no loader in the loop."""
        import jax
        import numpy as np

        params = jax.device_get(trainer.state.params)
        preds = trainer.model.apply({"params": params}, dataset.inputs)
        return float(np.mean(np.square(np.asarray(preds) - dataset.targets)))

    @pytest.mark.parametrize("n_eval", [40, 64, 37])
    def test_matches_handmade_mean_on_ragged_sets(self, n_eval):
        """Eval loss == the true distinct-sample mean even when the eval set
        is not divisible by the batch (serial: no wrap-pad needed either)."""
        import numpy as np

        from distributed_pytorch_tpu import ShardedLoader
        from distributed_pytorch_tpu.utils.data import MaterializedDataset

        trainer = self._trainer()
        eval_ds = MaterializedDataset(n_eval, seed=7)
        got = trainer.evaluate(ShardedLoader(eval_ds, 16))
        np.testing.assert_allclose(got, self._exact_mse(trainer, eval_ds), rtol=1e-5)

    @pytest.mark.parametrize("n_eval", [37, 52, 64])
    def test_exact_on_mesh_with_wrap_padding(self, n_eval):
        """On a mesh every ragged final batch IS wrap-padded (P('data') needs
        full batches); the padded duplicates must not bias the mean."""
        import numpy as np

        from distributed_pytorch_tpu import ShardedLoader, make_mesh
        from distributed_pytorch_tpu.utils.data import MaterializedDataset

        mesh = make_mesh()
        trainer = self._trainer(mesh=mesh)
        eval_ds = MaterializedDataset(n_eval, seed=11)
        got = trainer.evaluate(ShardedLoader(eval_ds, 16))
        np.testing.assert_allclose(got, self._exact_mse(trainer, eval_ds), rtol=1e-5)

    def test_exact_across_loader_shards(self):
        """Sharded loaders wrap-pad at the SHARD level too (DistributedSampler
        semantics); summing both shards' weighted sums must still be exact."""
        import numpy as np

        from distributed_pytorch_tpu import ShardedLoader
        from distributed_pytorch_tpu.utils.data import MaterializedDataset

        trainer = self._trainer()
        eval_ds = MaterializedDataset(41, seed=3)  # odd: shards get 21 padded rows
        per_shard = []
        for idx in range(2):
            loader = ShardedLoader(eval_ds, 8, num_shards=2, shard_index=idx)
            weights = np.concatenate(loader.batch_weight_table())
            indices = np.concatenate(loader.batch_index_table())
            per_shard.append((indices, weights))
        # Disjoint + exhaustive: rows with weight 1 across both shards are
        # exactly the 41 distinct samples, each once.
        real = np.concatenate([i[w > 0] for i, w in per_shard])
        assert sorted(real.tolist()) == list(range(41))

    @pytest.mark.slow
    def test_accuracy_metric(self):
        """metric_fns adds exact per-sample accuracy; returns a dict."""
        import numpy as np

        import jax.numpy as jnp
        import optax

        from distributed_pytorch_tpu import ShardedLoader, Trainer
        from distributed_pytorch_tpu.models.resnet import ResNet18
        from distributed_pytorch_tpu.training.losses import (
            per_sample_accuracy,
            softmax_cross_entropy_loss,
        )
        from distributed_pytorch_tpu.utils.data import ArrayDataset

        rng = np.random.default_rng(0)
        train = ArrayDataset(
            rng.standard_normal((8, 32, 32, 3)).astype(np.float32),
            rng.integers(0, 10, size=(8,)).astype(np.int32),
        )
        eval_ds = ArrayDataset(
            rng.standard_normal((11, 32, 32, 3)).astype(np.float32),  # ragged
            rng.integers(0, 10, size=(11,)).astype(np.int32),
        )
        trainer = Trainer(
            ResNet18(num_classes=10, cifar_stem=True, dtype=jnp.float32),
            ShardedLoader(train, 8),
            optax.sgd(1e-2),
            0,
            loss_fn=softmax_cross_entropy_loss,
        )
        metrics = trainer.evaluate(
            ShardedLoader(eval_ds, 8), metric_fns={"accuracy": per_sample_accuracy}
        )
        assert set(metrics) == {"loss", "accuracy"}
        # Cross-check accuracy against a handmade argmax over all 11 samples.
        import jax

        logits = trainer.model.apply(
            {"params": trainer.state.params, **trainer.state.model_state},
            eval_ds.inputs, train=False,
        )
        expected = float(np.mean(np.argmax(np.asarray(logits), -1) == eval_ds.targets))
        np.testing.assert_allclose(metrics["accuracy"], expected, atol=1e-6)
        assert jax  # silence unused-import lint
