"""Paged-attention kernel + int8 KV pages: the ISSUE-19 contract.

Op level: the Pallas flash-decode kernel (run in interpret mode on the
CPU rig) must match the pure-XLA reference within float tolerance, the
reference must match the engine's inline gather math BITWISE (that is
what makes `paged_kernel="xla"` a no-op toggle), NULL-page (page 0)
garbage must never survive the visibility mask, and the int8 path must
dequantize to the same numbers the int8 reference computes.

Engine level: greedy tokens across the full toggle matrix (kernel
on/off x prefix_cache x overlap x speculative x mesh (1,1)/(1,8)) must
be identical to the kernel-off baseline on the fp path; the int8 path
is bounded by a perplexity tolerance instead (quantization legitimately
moves logits). Elastic snapshots carry a KV fingerprint and refuse
int8<->fp restores exactly like the mesh-geometry refusal.
"""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.ops import flash_autotune as fa
from distributed_pytorch_tpu.ops.paged_attention import (
    paged_attention,
    paged_attention_reference,
    resolve_kernel,
)
from distributed_pytorch_tpu.ops.quant import quantize_int8
from distributed_pytorch_tpu.serving import (
    EngineSnapshot,
    InferenceEngine,
    SamplingParams,
    drain_engine,
    make_serving_mesh,
    restore_engine,
)

# ----------------------------------------------------------------- op level


def make_problem(seed=0, s=3, h=4, kv_heads=2, d=8, page=4, pages_per_seq=4,
                 dtype=jnp.float32):
    """Mixed-liveness decode batch: row 0 mid-sequence, row 1 one token
    short of full, row 2 inactive (all-NULL table, len 0)."""
    rng = np.random.default_rng(seed)
    num_pages = 8
    q = jnp.asarray(rng.standard_normal((s, 1, h, d)), dtype)
    pool = (num_pages, page, kv_heads, d)
    k_pool = jnp.asarray(rng.standard_normal(pool), dtype)
    v_pool = jnp.asarray(rng.standard_normal(pool), dtype)
    bt = jnp.asarray([[3, 5, 0, 0], [1, 2, 4, 6], [0, 0, 0, 0]], jnp.int32)
    lens = jnp.asarray([6, 15, 0], jnp.int32)
    return q, k_pool, v_pool, bt[:s], lens[:s]


def quantize_pool(pool):
    qt = quantize_int8(pool, (3,))
    return qt.q, jnp.squeeze(qt.scale, -1)


class TestPagedAttentionOp:
    @pytest.mark.parametrize("npb", [1, 2, 4])
    def test_kernel_matches_reference_fp(self, npb):
        q, kp, vp, bt, lens = make_problem()
        ref = paged_attention_reference(q, kp, vp, bt, lens)
        out = paged_attention(
            q, kp, vp, bt, lens, kernel="interpret", pages_per_block=npb
        )
        assert out.shape == ref.shape
        np.testing.assert_allclose(out, ref, atol=2e-6, rtol=2e-6)

    def test_xla_mode_is_reference_bitwise(self):
        q, kp, vp, bt, lens = make_problem()
        ref = paged_attention_reference(q, kp, vp, bt, lens)
        out = paged_attention(q, kp, vp, bt, lens, kernel="xla")
        assert (np.asarray(out) == np.asarray(ref)).all()

    def test_null_page_garbage_never_survives(self):
        """Property: page 0 contents are invisible. Poisoning the NULL
        page with huge finite garbage changes NOTHING for live rows, in
        both the reference and the kernel."""
        q, kp, vp, bt, lens = make_problem()
        ref = paged_attention_reference(q, kp, vp, bt, lens)
        out = paged_attention(q, kp, vp, bt, lens, kernel="interpret")
        poison_k = kp.at[0].set(1e4)
        poison_v = vp.at[0].set(-1e4)
        ref_p = paged_attention_reference(q, poison_k, poison_v, bt, lens)
        out_p = paged_attention(
            q, poison_k, poison_v, bt, lens, kernel="interpret"
        )
        live = slice(0, 2)  # row 2 is inactive; only live rows must hold
        assert (np.asarray(ref_p)[live] == np.asarray(ref)[live]).all()
        assert (np.asarray(out_p)[live] == np.asarray(out)[live]).all()
        # Inactive rows still produce FINITE (discarded) output.
        assert np.isfinite(np.asarray(out_p)).all()
        assert np.isfinite(np.asarray(ref_p)).all()

    def test_padded_table_tail_is_masked(self):
        """Rows whose table is wider than their length read their padded
        NULL entries as masked positions: growing the table with NULL
        pages never changes the output."""
        q, kp, vp, bt, lens = make_problem()
        ref = paged_attention_reference(q, kp, vp, bt, lens)
        wide_bt = jnp.concatenate(
            [bt, jnp.zeros((bt.shape[0], 2), jnp.int32)], axis=1
        )
        ref_w = paged_attention_reference(q, kp, vp, wide_bt, lens)
        out_w = paged_attention(q, kp, vp, wide_bt, lens, kernel="interpret")
        np.testing.assert_allclose(ref_w, ref, atol=0, rtol=0)
        np.testing.assert_allclose(out_w, ref, atol=2e-6, rtol=2e-6)

    def test_int8_kernel_matches_int8_reference(self):
        q, kp, vp, bt, lens = make_problem()
        k8, ks = quantize_pool(kp)
        v8, vs = quantize_pool(vp)
        ref = paged_attention_reference(
            q, k8, v8, bt, lens, k_scale=ks, v_scale=vs
        )
        out = paged_attention(
            q, k8, v8, bt, lens, k_scale=ks, v_scale=vs, kernel="interpret"
        )
        np.testing.assert_allclose(out, ref, atol=2e-6, rtol=2e-6)
        # And the quantized result is close to (not equal to) the fp one.
        fp = paged_attention_reference(q, kp, vp, bt, lens)
        err = np.abs(np.asarray(ref) - np.asarray(fp)).max()
        assert 0 < err < 0.1

    def test_grouped_query_mapping(self):
        """GQA group mapping: with Hkv=2, H=8, each KV head serves 4 query
        heads; a per-kv-head perturbation must move exactly its group."""
        q, kp, vp, bt, lens = make_problem(h=8, kv_heads=2)
        ref = paged_attention_reference(q, kp, vp, bt, lens)
        out = paged_attention(q, kp, vp, bt, lens, kernel="interpret")
        np.testing.assert_allclose(out, ref, atol=2e-6, rtol=2e-6)
        bumped = paged_attention_reference(
            q, kp, vp.at[:, :, 0, :].add(1.0), bt, lens
        )
        delta = np.abs(np.asarray(bumped) - np.asarray(ref))
        # Query heads 0..3 read kv head 0 (moved); 4..7 read kv head 1.
        assert delta[0, :, :4, :].max() > 0
        assert delta[0, :, 4:, :].max() == 0

    def test_t_step_gt1_falls_back_to_reference(self):
        q, kp, vp, bt, lens = make_problem()
        q2 = jnp.concatenate([q, q], axis=1)  # t_step = 2 (prefill chunk)
        ref = paged_attention_reference(q2, kp, vp, bt, lens)
        out = paged_attention(q2, kp, vp, bt, lens, kernel="interpret")
        assert (np.asarray(out) == np.asarray(ref)).all()

    def test_resolve_kernel_validates(self):
        assert resolve_kernel("xla") == "xla"
        assert resolve_kernel("interpret") == "interpret"
        assert resolve_kernel(True) in ("pallas", "xla")
        assert resolve_kernel("auto") == resolve_kernel(None)
        with pytest.raises(ValueError, match="kernel"):
            resolve_kernel("cuda")

    def test_scale_pairing_validated(self):
        q, kp, vp, bt, lens = make_problem()
        k8, ks = quantize_pool(kp)
        with pytest.raises(ValueError, match="scale"):
            paged_attention(q, k8, vp, bt, lens, k_scale=ks, kernel="xla")

    def test_mesh_shard_map_parity(self):
        """The kernel under shard_map over the 'model' axis (the
        KV_POOL_SPEC head split) matches the unsharded reference on a
        (1,8) mesh, fp and int8."""
        q, kp, vp, bt, lens = make_problem(h=8, kv_heads=8)
        mesh = make_serving_mesh(1, 8)
        ref = paged_attention_reference(q, kp, vp, bt, lens)
        out = paged_attention(
            q, kp, vp, bt, lens, kernel="interpret", mesh=mesh
        )
        np.testing.assert_allclose(out, ref, atol=2e-6, rtol=2e-6)
        k8, ks = quantize_pool(kp)
        v8, vs = quantize_pool(vp)
        ref8 = paged_attention_reference(
            q, k8, v8, bt, lens, k_scale=ks, v_scale=vs
        )
        out8 = paged_attention(
            q, k8, v8, bt, lens, k_scale=ks, v_scale=vs,
            kernel="interpret", mesh=mesh,
        )
        np.testing.assert_allclose(out8, ref8, atol=2e-6, rtol=2e-6)

    def test_jit_composes(self):
        q, kp, vp, bt, lens = make_problem()
        fn = jax.jit(lambda *a: paged_attention(*a, kernel="interpret"))
        out = fn(q, kp, vp, bt, lens)
        ref = paged_attention_reference(q, kp, vp, bt, lens)
        np.testing.assert_allclose(out, ref, atol=2e-6, rtol=2e-6)


# ------------------------------------------------------- autotune family


@pytest.fixture
def _isolated_caches(tmp_path, monkeypatch):
    """Redirect every cache tier at empty temp state (same idiom as
    test_flash_autotune.py) so paged lookups hit the seeded table."""
    monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path / "xdg"))
    monkeypatch.delenv("FLASH_BLOCKS_TABLE", raising=False)
    monkeypatch.delenv("FLASH_AUTOTUNE", raising=False)
    monkeypatch.setattr(fa, "_runtime_cache", {})
    fa._load_table_file.cache_clear()
    yield
    fa._load_table_file.cache_clear()


class TestPagedAutotune:
    def test_candidates_are_bounded_powers_of_two(self):
        cands = fa.paged_candidates(64, 16)
        assert cands[0] == 1
        for c in cands:
            assert c & (c - 1) == 0
            assert c * 16 <= 4096
        assert fa.paged_candidates(1, 8) == [1]

    def test_seeded_cpu_entry_no_sweep(self, _isolated_caches):
        """CI never autotunes: the shipped PAGED_DEFAULT_TABLE entry for
        'cpu' answers lookups directly."""
        npb = fa.lookup_paged(256, 16, 64, device_kind="cpu")
        assert npb == fa.PAGED_DEFAULT_TABLE["cpu"]
        # And nothing was swept or persisted to disk.
        assert fa._load_disk_cache() == {}

    def test_family_key_disjoint_from_flash(self):
        pk = fa._paged_key("cpu", 2048, 16, 64, "float32")
        flash = fa._key("cpu", 2048, 64, "float32", False)
        assert pk != flash
        assert fa.PAGED_FAMILY in pk[3] and "p16" in pk[3]

    def test_lookup_clips_to_legal_candidates(self, _isolated_caches):
        # Table width 2 pages: the seeded npb must clip down to <= 2.
        npb = fa.lookup_paged(16, 8, 8, device_kind="tpu v5 lite")
        assert npb in fa.paged_candidates(2, 8)

    def test_table_file_tier_wins(self, _isolated_caches, tmp_path,
                                  monkeypatch):
        key = fa._paged_key("cpu", 256, 16, 64, "float32")
        path = tmp_path / "table.json"
        path.write_text(json.dumps({json.dumps(list(key)): [8, 128]}))
        monkeypatch.setenv("FLASH_BLOCKS_TABLE", str(path))
        assert fa.lookup_paged(256, 16, 64, device_kind="cpu") == 8

    def test_autotune_paged_persists_winner(self, _isolated_caches):
        npb = fa.autotune_paged(16, 4, 8, slots=2, kv_heads=2, steps=1)
        assert npb in fa.paged_candidates(4, 4)
        # Cached: a second call returns without sweeping (runtime tier).
        assert fa.lookup_paged(16, 4, 8) == npb
        disk = fa._load_disk_cache()
        key = fa._paged_key(fa._device_kind(), 16, 4, 8, "float32")
        assert disk[key] == (npb, npb * 4)


# -------------------------------------------------- engine parity matrix

MESH_LM = dict(
    vocab_size=64, d_model=32, n_layers=2, n_heads=8, d_ff=64,
    dtype=jnp.float32,
)
PROMPTS = [[1, 2, 3, 4], [5, 6, 7], [1, 2, 3, 9, 10]]
MAX_NEW = 5
ENGINE_KW = dict(
    max_slots=4, max_seq_len=32, page_size=8, token_budget=32,
    max_prefill_chunk=16,
)


@pytest.fixture(scope="module")
def model_and_params():
    model = TransformerLM(**MESH_LM)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


@pytest.fixture(scope="module")
def draft_and_params():
    draft = TransformerLM(
        vocab_size=64, d_model=16, n_layers=1, n_heads=8, d_ff=32,
        dtype=jnp.float32,
    )
    dparams = draft.init(
        jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return draft, dparams


def run_engine(model, params, *, mesh=None, prefix=True, overlap=True,
               spec=None, **extra):
    kw = dict(ENGINE_KW)
    if spec is not None:
        draft, dparams = spec
        kw.update(draft_model=draft, draft_params=dparams, gamma=3)
    eng = InferenceEngine(
        model, params, mesh=mesh, prefix_cache=prefix, overlap=overlap,
        **kw, **extra,
    )
    ids = [
        eng.submit(p, SamplingParams(max_new_tokens=MAX_NEW))
        for p in PROMPTS
    ]
    eng.run()
    out = [eng.poll(i).generated for i in ids]
    eng.close()
    return out, eng


@pytest.fixture(scope="module")
def baseline_greedy(model_and_params):
    out, _ = run_engine(*model_and_params)
    return out


class TestEngineKernelParity:
    """fp path: kernel on/off must be token-identical everywhere. On the
    CPU rig paged_kernel=True resolves to the XLA reference (bitwise by
    the op tests above); "interpret" runs the actual kernel math."""

    @pytest.mark.parametrize("kernel", [True, "xla", "interpret"])
    @pytest.mark.parametrize("prefix", [True, False])
    def test_kernel_matrix_unsharded(self, model_and_params,
                                     baseline_greedy, kernel, prefix):
        out, eng = run_engine(
            *model_and_params, prefix=prefix, paged_kernel=kernel
        )
        assert out == baseline_greedy
        assert eng.paged_kernel in ("auto", "xla", "interpret")

    @pytest.mark.parametrize("overlap", [True, False])
    def test_kernel_overlap_toggle(self, model_and_params,
                                   baseline_greedy, overlap):
        out, _ = run_engine(
            *model_and_params, overlap=overlap, paged_kernel=True
        )
        assert out == baseline_greedy

    def test_kernel_speculative(self, model_and_params, draft_and_params,
                                baseline_greedy):
        out, _ = run_engine(
            *model_and_params, spec=draft_and_params, paged_kernel=True
        )
        assert out == baseline_greedy

    @pytest.mark.parametrize("shape", [(1, 1), (1, 8)])
    def test_kernel_mesh(self, model_and_params, baseline_greedy, shape):
        out, eng = run_engine(
            *model_and_params, mesh=make_serving_mesh(*shape),
            paged_kernel=True,
        )
        assert out == baseline_greedy
        assert eng._sharded_programs >= 3

    @pytest.mark.parametrize("shape", [(1, 1), (1, 8)])
    def test_kernel_interpret_mesh(self, model_and_params,
                                   baseline_greedy, shape):
        out, _ = run_engine(
            *model_and_params, mesh=make_serving_mesh(*shape),
            paged_kernel="interpret",
        )
        assert out == baseline_greedy

    def test_paged_program_name(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngine(
            model, params, xla_ledger=True, paged_kernel=True, **ENGINE_KW
        )
        rid = eng.submit([1, 2, 3], SamplingParams(max_new_tokens=3))
        eng.run()
        assert eng.poll(rid).finished
        names = {r.name for r in eng.xla.programs.values()}
        eng.close()
        assert any(n.startswith("decode_step_paged") for n in names)
        assert not any(n == "decode_step" for n in names)

    def test_bad_kernel_mode_fails_at_init(self, model_and_params):
        model, params = model_and_params
        with pytest.raises(ValueError, match="kernel"):
            InferenceEngine(
                model, params, paged_kernel="cuda", **ENGINE_KW
            )


# ------------------------------------------------------------ int8 path


class TestInt8KV:
    def test_cache_layout(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngine(
            model, params, kv_quant="int8", **ENGINE_KW
        )
        leaves = jax.tree_util.tree_leaves(eng.pools["target"])
        dtypes = sorted({str(x.dtype) for x in leaves})
        assert dtypes == ["float32", "int8"]
        for x in leaves:
            assert x.ndim in (3, 4)  # scale pools ride alongside
        assert eng.kv_fingerprint == "int8"
        eng.close()

    def test_rejects_unknown_quant(self, model_and_params):
        model, params = model_and_params
        with pytest.raises(ValueError, match="kv_quant"):
            InferenceEngine(model, params, kv_quant="int4", **ENGINE_KW)

    @pytest.mark.parametrize("kernel", [False, True])
    def test_int8_perplexity_tolerance(self, model_and_params, kernel):
        """Teacher-forced decode through the paged cache: the int8 path's
        per-token NLL over a fixed stream must stay within 2% of the fp
        path's (greedy tokens may legitimately differ under quantization;
        the distribution must not move materially)."""
        model, params = model_and_params
        toks = np.asarray(
            np.random.default_rng(7).integers(1, 64, (2, 12))
        )

        def mean_nll(kv_quant):
            m = model.clone(
                decode=True, page_size=4, num_pages=17, kv_quant=kv_quant,
                paged_kernel="interpret" if kernel else "",
            )
            cache = m.init(
                jax.random.PRNGKey(0), jnp.zeros((2, 1), jnp.int32)
            )["cache"]
            bt = jnp.asarray(
                [[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32
            )
            nll = []
            for t in range(toks.shape[1] - 1):
                lens = jnp.full((2,), t, jnp.int32)
                logits, mut = m.apply(
                    {"params": params, "cache": cache},
                    jnp.asarray(toks[:, t:t + 1]),
                    block_tables=bt, seq_lens=lens, mutable=["cache"],
                )
                cache = mut["cache"]
                logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32))
                nll.append(
                    -np.asarray(logp)[np.arange(2), toks[:, t + 1]].mean()
                )
            return float(np.mean(nll))

        fp = mean_nll("")
        q8 = mean_nll("int8")
        assert abs(q8 - fp) / fp < 0.02, (fp, q8)

    def test_int8_halves_page_bytes(self, model_and_params):
        model, params = model_and_params
        fp = InferenceEngine(model, params, **ENGINE_KW)
        q8 = InferenceEngine(model, params, kv_quant="int8", **ENGINE_KW)
        bytes_fp = sum(
            x.nbytes for x in jax.tree_util.tree_leaves(fp.pools["target"])
        )
        bytes_q8 = sum(
            x.nbytes for x in jax.tree_util.tree_leaves(q8.pools["target"])
        )
        fp.close()
        q8.close()
        # fp32 pools: int8 payload = 1/4, f32 scales add 1/D = 1/8.
        d = MESH_LM["d_model"] // MESH_LM["n_heads"]
        assert bytes_q8 * 8 == bytes_fp * (2 + 8 // d * 1)

    def test_int8_engine_runs_all_toggles(self, model_and_params,
                                          draft_and_params):
        """int8 output is engine-path-invariant: kernel modes, prefix,
        speculative, and mesh all agree with the int8 gather baseline."""
        base, _ = run_engine(*model_and_params, kv_quant="int8")
        for extra in (
            dict(paged_kernel=True),
            dict(paged_kernel="interpret"),
            dict(prefix=False),
            dict(spec=draft_and_params),
            dict(mesh=make_serving_mesh(1, 8), paged_kernel=True),
        ):
            out, _ = run_engine(*model_and_params, kv_quant="int8", **extra)
            assert out == base, extra


# --------------------------------------------------- elastic fingerprint


class TestKvFingerprint:
    def _snap(self, model, params, **ekw):
        eng = InferenceEngine(model, params, **ENGINE_KW, **ekw)
        eng.submit([1, 2, 3, 4, 5], SamplingParams(max_new_tokens=8))
        eng.step()
        snap = drain_engine(eng)
        eng.close()
        return snap

    def test_snapshot_carries_kv_fingerprint(self, model_and_params):
        model, params = model_and_params
        assert self._snap(model, params).kv == "fp"
        assert self._snap(model, params, kv_quant="int8").kv == "int8"

    def test_restore_refuses_kv_mismatch(self, model_and_params):
        model, params = model_and_params
        snap = self._snap(model, params, kv_quant="int8")
        fp_engine = InferenceEngine(model, params, **ENGINE_KW)
        with pytest.raises(ValueError, match="int8"):
            restore_engine(fp_engine, snap)
        fp_engine.close()

    def test_restore_matching_int8_round_trips(self, model_and_params):
        model, params = model_and_params
        snap = self._snap(model, params, kv_quant="int8")
        target = InferenceEngine(
            model, params, kv_quant="int8", **ENGINE_KW
        )
        ids = restore_engine(target, snap)
        target.run()
        assert all(target.poll(i).finished for i in ids)
        target.close()

    def test_old_snapshots_decode_as_fp(self, model_and_params):
        """Wire backcompat: snapshots written before the kv field decode
        with kv='fp' (mirrors the mesh-field default)."""
        model, params = model_and_params
        snap = self._snap(model, params)
        doc = json.loads(snap.to_json())
        del doc["kv"]
        old = EngineSnapshot.from_json(json.dumps(doc))
        assert old.kv == "fp"
        assert dataclasses.replace(old, kv=snap.kv) == snap
