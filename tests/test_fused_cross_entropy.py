"""Fused chunked-vocab cross-entropy vs the dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_pytorch_tpu.ops.fused_cross_entropy import (
    fused_linear_cross_entropy,
)


def make_case(n=24, d=8, vocab=40, seed=0):
    rng = np.random.default_rng(seed)
    hidden = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    weight = jnp.asarray(rng.standard_normal((d, vocab)) * 0.3, jnp.float32)
    bias = jnp.asarray(rng.standard_normal((vocab,)) * 0.1, jnp.float32)
    targets = jnp.asarray(rng.integers(0, vocab, (n,)), jnp.int32)
    return hidden, weight, bias, targets


def dense_loss(hidden, weight, bias, targets):
    logits = hidden @ weight + (0 if bias is None else bias)
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    )


@pytest.mark.parametrize("chunk", [8, 20, 40])
@pytest.mark.parametrize("with_bias", [True, False])
def test_matches_dense(chunk, with_bias):
    hidden, weight, bias, targets = make_case()
    b = bias if with_bias else None
    fused = fused_linear_cross_entropy(hidden, weight, b, targets, chunk)
    ref = dense_loss(hidden, weight, b, targets)
    np.testing.assert_allclose(float(fused), float(ref), rtol=1e-6)


def test_grads_match_dense():
    hidden, weight, bias, targets = make_case()
    gf = jax.grad(
        lambda h, w, b: fused_linear_cross_entropy(h, w, b, targets, 8),
        (0, 1, 2),
    )(hidden, weight, bias)
    gd = jax.grad(
        lambda h, w, b: dense_loss(h, w, b, targets), (0, 1, 2)
    )(hidden, weight, bias)
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-6)


def test_grads_match_dense_no_bias():
    hidden, weight, _, targets = make_case()
    gf = jax.grad(
        lambda h, w: fused_linear_cross_entropy(h, w, None, targets, 20),
        (0, 1),
    )(hidden, weight)
    gd = jax.grad(lambda h, w: dense_loss(h, w, None, targets), (0, 1))(
        hidden, weight
    )
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-6)


def test_under_jit_and_scaled_upstream_gradient():
    hidden, weight, bias, targets = make_case()

    @jax.jit
    def f(h, w, b):
        return 3.5 * fused_linear_cross_entropy(h, w, b, targets, 8)

    gf = jax.grad(f, (0, 1, 2))(hidden, weight, bias)
    gd = jax.grad(
        lambda h, w, b: 3.5 * dense_loss(h, w, b, targets), (0, 1, 2)
    )(hidden, weight, bias)
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-5)


def test_indivisible_chunk_raises():
    hidden, weight, bias, targets = make_case(vocab=40)
    with pytest.raises(ValueError, match="divisible"):
        fused_linear_cross_entropy(hidden, weight, bias, targets, 16)


# ---------------------------------------------------------------------------
# Fused head wired into TransformerLM (VERDICT round 1, item 1): the flagship
# path must produce the same loss/grads with and without the fused head, from
# an identical parameter tree.
# ---------------------------------------------------------------------------


def _lm_pair(vocab=64, chunk=16):
    import optax

    from distributed_pytorch_tpu.models.transformer import TransformerLM
    from distributed_pytorch_tpu.training.losses import (
        softmax_cross_entropy_loss,
    )
    from distributed_pytorch_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )

    kw = dict(vocab_size=vocab, d_model=32, n_layers=2, n_heads=2, d_ff=64)
    dense = TransformerLM(**kw)
    fused = TransformerLM(**kw, fused_head_chunk=chunk)
    tokens = np.asarray(
        np.random.default_rng(0).integers(0, vocab, (4, 16)), np.int32
    )
    targets = np.asarray(
        np.random.default_rng(1).integers(0, vocab, (4, 16)), np.int32
    )
    opt = optax.sgd(1e-2)
    sd = create_train_state(dense, opt, tokens)
    sf = create_train_state(fused, opt, tokens)

    def lm_shift_loss(logits, tgt):
        return softmax_cross_entropy_loss(logits, tgt)

    step_d = make_train_step(dense.apply, opt, lm_shift_loss)
    step_f = make_train_step(
        fused.apply, opt, lambda out, _: out, apply_takes_targets=True
    )
    return sd, sf, step_d, step_f, (tokens, targets)


def test_lm_fused_head_param_tree_identical():
    sd, sf, *_ = _lm_pair()
    td = jax.tree_util.tree_structure(sd.params)
    tf = jax.tree_util.tree_structure(sf.params)
    assert td == tf
    # Same pinned-seed init values too: checkpoints move freely between modes.
    for a, b in zip(
        jax.tree_util.tree_leaves(sd.params), jax.tree_util.tree_leaves(sf.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_lm_fused_head_loss_and_grads_match_dense():
    sd, sf, step_d, step_f, batch = _lm_pair()
    for _ in range(3):  # a few optimizer steps: grads must match too
        sd, loss_d = step_d(sd, batch)
        sf, loss_f = step_f(sf, batch)
        np.testing.assert_allclose(float(loss_d), float(loss_f), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(sd.params), jax.tree_util.tree_leaves(sf.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_lm_fused_head_indivisible_vocab_raises():
    from distributed_pytorch_tpu.models.transformer import TransformerLM

    model = TransformerLM(
        vocab_size=50, d_model=16, n_layers=1, n_heads=2, d_ff=32,
        fused_head_chunk=16,  # 50 % 16 != 0: loud error, no silent downgrade
    )
    tokens = np.zeros((2, 8), np.int32)
    with pytest.raises(ValueError, match="divisible"):
        model.init(jax.random.PRNGKey(0), tokens)
