"""Fused chunked-vocab cross-entropy vs the dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_pytorch_tpu.ops.fused_cross_entropy import (
    fused_linear_cross_entropy,
)


def make_case(n=24, d=8, vocab=40, seed=0):
    rng = np.random.default_rng(seed)
    hidden = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    weight = jnp.asarray(rng.standard_normal((d, vocab)) * 0.3, jnp.float32)
    bias = jnp.asarray(rng.standard_normal((vocab,)) * 0.1, jnp.float32)
    targets = jnp.asarray(rng.integers(0, vocab, (n,)), jnp.int32)
    return hidden, weight, bias, targets


def dense_loss(hidden, weight, bias, targets):
    logits = hidden @ weight + (0 if bias is None else bias)
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    )


@pytest.mark.parametrize("chunk", [8, 20, 40])
@pytest.mark.parametrize("with_bias", [True, False])
def test_matches_dense(chunk, with_bias):
    hidden, weight, bias, targets = make_case()
    b = bias if with_bias else None
    fused = fused_linear_cross_entropy(hidden, weight, b, targets, chunk)
    ref = dense_loss(hidden, weight, b, targets)
    np.testing.assert_allclose(float(fused), float(ref), rtol=1e-6)


def test_grads_match_dense():
    hidden, weight, bias, targets = make_case()
    gf = jax.grad(
        lambda h, w, b: fused_linear_cross_entropy(h, w, b, targets, 8),
        (0, 1, 2),
    )(hidden, weight, bias)
    gd = jax.grad(
        lambda h, w, b: dense_loss(h, w, b, targets), (0, 1, 2)
    )(hidden, weight, bias)
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-6)


def test_grads_match_dense_no_bias():
    hidden, weight, _, targets = make_case()
    gf = jax.grad(
        lambda h, w: fused_linear_cross_entropy(h, w, None, targets, 20),
        (0, 1),
    )(hidden, weight)
    gd = jax.grad(lambda h, w: dense_loss(h, w, None, targets), (0, 1))(
        hidden, weight
    )
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-6)


def test_under_jit_and_scaled_upstream_gradient():
    hidden, weight, bias, targets = make_case()

    @jax.jit
    def f(h, w, b):
        return 3.5 * fused_linear_cross_entropy(h, w, b, targets, 8)

    gf = jax.grad(f, (0, 1, 2))(hidden, weight, bias)
    gd = jax.grad(
        lambda h, w, b: 3.5 * dense_loss(h, w, b, targets), (0, 1, 2)
    )(hidden, weight, bias)
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-5)


def test_indivisible_chunk_raises():
    hidden, weight, bias, targets = make_case(vocab=40)
    with pytest.raises(ValueError, match="divisible"):
        fused_linear_cross_entropy(hidden, weight, bias, targets, 16)
