"""Pipeline-parallelism tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from distributed_pytorch_tpu.models.pipeline_lm import PipelinedTransformerLM
from distributed_pytorch_tpu.parallel.mesh import make_mesh
from distributed_pytorch_tpu.parallel.partitioning import (
    make_param_specs,
    make_state_shardings,
    shard_train_state,
)
from distributed_pytorch_tpu.parallel.pipeline import (
    PIPELINE_STAGE_RULES,
    pipeline_apply,
)
from distributed_pytorch_tpu.parallel.sharding import put_global_batch
from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss
from distributed_pytorch_tpu.training.train_step import (
    create_train_state,
    make_train_step,
)


def test_pipeline_apply_matches_serial_chain():
    """Pipelined execution == sequentially applying the stages."""
    mesh = make_mesh({"stage": 4}, devices=jax.devices()[:4])
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((4, 8, 8)) * 0.3, jnp.float32)
    b = jnp.asarray(rng.standard_normal((4, 8)) * 0.1, jnp.float32)
    x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)

    def stage_fn(params, xin):
        return jnp.tanh(xin @ params["w"] + params["b"])

    out = pipeline_apply(
        stage_fn, {"w": w, "b": b}, x,
        mesh=mesh, num_microbatches=4, data_axis=None,
    )
    expected = x
    for s in range(4):
        expected = stage_fn({"w": w[s], "b": b[s]}, expected)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-5)


@pytest.mark.slow
def test_pipeline_apply_grads_match_serial():
    """Gradients flow back through the ppermute ring and match the serial
    chain's gradients."""
    mesh = make_mesh({"stage": 4}, devices=jax.devices()[:4])
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.standard_normal((4, 6, 6)) * 0.3, jnp.float32)
    x = jnp.asarray(rng.standard_normal((8, 6)), jnp.float32)

    def stage_fn(params, xin):
        return jnp.tanh(xin @ params)

    def piped_loss(w):
        return jnp.sum(
            pipeline_apply(
                stage_fn, w, x, mesh=mesh, num_microbatches=2, data_axis=None
            )
            ** 2
        )

    def serial_loss(w):
        h = x
        for s in range(4):
            h = stage_fn(w[s], h)
        return jnp.sum(h**2)

    g_piped = jax.grad(piped_loss)(w)
    g_serial = jax.grad(serial_loss)(w)
    np.testing.assert_allclose(np.asarray(g_piped), np.asarray(g_serial), atol=1e-4)


@pytest.mark.slow
def test_pipelined_lm_matches_serial_fallback():
    """The same params give the same logits with the pipeline on a stage mesh
    vs the serial chain fallback (mesh=None)."""
    rng = np.random.default_rng(2)
    tokens = rng.integers(0, 32, (8, 12), dtype=np.int32)
    kw = dict(
        vocab_size=32, d_model=16, n_stages=4, layers_per_stage=1,
        n_heads=2, d_ff=32, num_microbatches=2,
    )
    serial = PipelinedTransformerLM(**kw)
    variables = serial.init(jax.random.PRNGKey(0), tokens)
    logits_serial = serial.apply(variables, tokens)

    mesh = make_mesh({"data": 2, "stage": 4})
    piped = PipelinedTransformerLM(**kw, mesh=mesh)
    logits_piped = jax.jit(piped.apply)(variables, jnp.asarray(tokens))
    np.testing.assert_allclose(
        np.asarray(logits_piped), np.asarray(logits_serial), atol=2e-4
    )


@pytest.mark.slow
def test_pp_training_loss_decreases_with_sharded_stages():
    """Full DP x PP train loop: stage params sharded P('stage'), loss falls."""
    mesh = make_mesh({"data": 2, "stage": 4})
    model = PipelinedTransformerLM(
        vocab_size=32, d_model=16, n_stages=4, layers_per_stage=1,
        n_heads=2, d_ff=32, num_microbatches=2, mesh=mesh,
    )
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, 32, (8, 13), dtype=np.int32)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    optimizer = optax.adam(1e-2)
    state = create_train_state(model, optimizer, inputs)
    specs = make_param_specs(state.params, PIPELINE_STAGE_RULES, mesh=mesh)
    stage_leaves = [
        s
        for path, s in jtu.tree_leaves_with_path(
            specs, is_leaf=lambda x: isinstance(x, P)
        )
        if "stages" in str(path)
    ]
    assert stage_leaves and all(s == P("stage") for s in stage_leaves)
    shardings = make_state_shardings(mesh, state, specs)
    state = shard_train_state(state, shardings)
    step = make_train_step(
        model.apply, optimizer, softmax_cross_entropy_loss,
        mesh=mesh, state_sharding=shardings,
    )
    batch = put_global_batch(mesh, (inputs, targets))
    losses = []
    for _ in range(8):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # Stage params are physically distributed.
    stacked = state.params["stages"]
    leaf = jtu.tree_leaves(stacked)[0]
    assert not leaf.sharding.is_fully_replicated


# ------------------------------------------------------------------ 1F1B


class Test1F1B:
    """pipeline_1f1b_grads (VERDICT r04 item 7): the memory-bounded
    PipeDream-flush schedule must reproduce the serial chain's loss and
    every gradient (stage params, head params, input) exactly."""

    S = 4

    def _setup(self, m=8, d=6, batch=16, seed=0):
        rng = np.random.default_rng(seed)
        w = jnp.asarray(rng.standard_normal((self.S, d, d)) * 0.4, jnp.float32)
        b = jnp.asarray(rng.standard_normal((self.S, d)) * 0.1, jnp.float32)
        head = jnp.asarray(rng.standard_normal((d, 3)) * 0.4, jnp.float32)
        x = jnp.asarray(rng.standard_normal((batch, d)), jnp.float32)
        t = jnp.asarray(rng.standard_normal((batch, 3)), jnp.float32)
        return {"w": w, "b": b}, head, x, t

    @staticmethod
    def _stage_fn(params, xin):
        return jnp.tanh(xin @ params["w"] + params["b"])

    @staticmethod
    def _last_fn(head, y, tgt):
        return jnp.mean((y @ head - tgt) ** 2)

    def _serial_reference(self, stacked, head, x, t):
        def loss_fn(stacked, head, x):
            h = x
            for s in range(self.S):
                h = self._stage_fn(
                    jax.tree_util.tree_map(lambda p, s=s: p[s], stacked), h
                )
            return self._last_fn(head, h, t)

        return jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(stacked, head, x)

    @pytest.mark.parametrize("m", [2, 4, 8])  # m < S, m == S, m > S
    def test_matches_serial_gradients(self, m):
        from distributed_pytorch_tpu.parallel.pipeline import (
            pipeline_1f1b_grads,
        )

        mesh = make_mesh({"stage": self.S}, devices=jax.devices()[: self.S])
        stacked, head, x, t = self._setup(m=m)
        loss, gp, glp, dx = pipeline_1f1b_grads(
            self._stage_fn, stacked, self._last_fn, head, x, t,
            mesh=mesh, num_microbatches=m, data_axis=None,
        )
        ref_loss, (ref_gp, ref_glp, ref_dx) = self._serial_reference(
            stacked, head, x, t
        )
        np.testing.assert_allclose(
            float(loss), float(ref_loss), rtol=1e-5, err_msg=f"m={m}"
        )
        for key in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(gp[key]), np.asarray(ref_gp[key]),
                rtol=1e-4, atol=1e-5, err_msg=f"m={m} {key}",
            )
        np.testing.assert_allclose(
            np.asarray(glp), np.asarray(ref_glp), rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(dx), np.asarray(ref_dx), rtol=1e-4, atol=1e-5
        )

    def test_mixed_precision_promoting_stage_fn(self):
        """bf16 activations over f32 params promote to f32 inside the
        stages; the lax.cond branch signatures and the streamed carries
        must follow the PROMOTED dtype instead of crashing at trace
        (round-5 review finding), and gradients must match the serial
        chain at bf16-appropriate tolerance."""
        from distributed_pytorch_tpu.parallel.pipeline import (
            pipeline_1f1b_grads,
        )

        mesh = make_mesh({"stage": self.S}, devices=jax.devices()[: self.S])
        stacked, head, x, t = self._setup(m=4)
        x16 = x.astype(jnp.bfloat16)  # f32 params x bf16 input -> f32 out

        loss, gp, glp, dx = pipeline_1f1b_grads(
            self._stage_fn, stacked, self._last_fn, head, x16, t,
            mesh=mesh, num_microbatches=4, data_axis=None,
        )
        assert dx.dtype == jnp.bfloat16  # cotangent follows x's dtype
        ref_loss, (ref_gp, ref_glp, ref_dx) = self._serial_reference(
            stacked, head, x16.astype(jnp.float32), t
        )
        np.testing.assert_allclose(
            float(loss), float(ref_loss), rtol=2e-2
        )
        for key in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(gp[key]), np.asarray(ref_gp[key]),
                rtol=5e-2, atol=5e-3,
            )
        np.testing.assert_allclose(
            np.asarray(glp), np.asarray(ref_glp), rtol=5e-2, atol=5e-3
        )

    def test_composes_with_data_parallelism(self):
        from distributed_pytorch_tpu.parallel.pipeline import (
            pipeline_1f1b_grads,
        )

        mesh = make_mesh({"data": 2, "stage": self.S})
        stacked, head, x, t = self._setup(m=4, batch=16)
        loss, gp, glp, dx = pipeline_1f1b_grads(
            self._stage_fn, stacked, self._last_fn, head, x, t,
            mesh=mesh, num_microbatches=4,
        )
        # DP x PP reference: the global-batch mean is the mean of per-shard
        # means (equal shards), which is the plain full-batch mean.
        ref_loss, (ref_gp, ref_glp, ref_dx) = self._serial_reference(
            stacked, head, x, t
        )
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        for key in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(gp[key]), np.asarray(ref_gp[key]),
                rtol=1e-4, atol=1e-5, err_msg=key,
            )
        np.testing.assert_allclose(
            np.asarray(glp), np.asarray(ref_glp), rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(dx), np.asarray(ref_dx), rtol=1e-4, atol=1e-5
        )

    def test_serial_fallback_on_trivial_axis(self):
        from distributed_pytorch_tpu.parallel.pipeline import (
            pipeline_1f1b_grads,
        )

        mesh = make_mesh({"stage": 1}, devices=jax.devices()[:1])
        stacked, head, x, t = self._setup(m=4)
        loss, gp, glp, dx = pipeline_1f1b_grads(
            self._stage_fn, stacked, self._last_fn, head, x, t,
            mesh=mesh, num_microbatches=4, data_axis=None,
        )
        ref_loss, (ref_gp, ref_glp, ref_dx) = self._serial_reference(
            stacked, head, x, t
        )
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(gp["w"]), np.asarray(ref_gp["w"]), rtol=1e-5
        )

    def test_training_loss_decreases(self):
        """SGD on 1F1B grads actually trains (stage AND head params move)."""
        from distributed_pytorch_tpu.parallel.pipeline import (
            pipeline_1f1b_grads,
        )

        mesh = make_mesh({"stage": self.S}, devices=jax.devices()[: self.S])
        stacked, head, x, t = self._setup(m=4)
        losses = []
        for _ in range(12):
            loss, gp, glp, _ = pipeline_1f1b_grads(
                self._stage_fn, stacked, self._last_fn, head, x, t,
                mesh=mesh, num_microbatches=4, data_axis=None,
            )
            losses.append(float(loss))
            stacked = jax.tree_util.tree_map(
                lambda p, g: p - 0.1 * g, stacked, gp
            )
            head = head - 0.1 * glp
        # The random-target regression has an irreducible residual; assert a
        # clear, steady descent rather than an arbitrary halving.
        assert losses[-1] < 0.8 * losses[0], losses
        assert all(b < a for a, b in zip(losses, losses[1:])), losses

    def test_with_dx_false_matches_and_returns_none(self):
        from distributed_pytorch_tpu.parallel.pipeline import (
            pipeline_1f1b_grads,
        )

        mesh = make_mesh({"stage": self.S}, devices=jax.devices()[: self.S])
        stacked, head, x, t = self._setup(m=4)
        loss, gp, glp, dx = pipeline_1f1b_grads(
            self._stage_fn, stacked, self._last_fn, head, x, t,
            mesh=mesh, num_microbatches=4, data_axis=None, with_dx=False,
        )
        assert dx is None
        ref_loss, (ref_gp, _, _) = self._serial_reference(stacked, head, x, t)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(gp["w"]), np.asarray(ref_gp["w"]), rtol=1e-4, atol=1e-5
        )

    def test_dx_is_identical_on_every_stage_shard(self):
        """dx leaves the shard_map stage-REPLICATED for real: every device's
        shard must hold the same (correct) values, not just stage 0's
        (host-side np.asarray reads only the first shard, which hid this)."""
        from distributed_pytorch_tpu.parallel.pipeline import (
            pipeline_1f1b_grads,
        )

        mesh = make_mesh({"stage": self.S}, devices=jax.devices()[: self.S])
        stacked, head, x, t = self._setup(m=4)
        _, _, _, dx = pipeline_1f1b_grads(
            self._stage_fn, stacked, self._last_fn, head, x, t,
            mesh=mesh, num_microbatches=4, data_axis=None,
        )
        _, (_, _, ref_dx) = self._serial_reference(stacked, head, x, t)
        for shard in dx.addressable_shards:
            np.testing.assert_allclose(
                np.asarray(shard.data), np.asarray(ref_dx),
                rtol=1e-4, atol=1e-5, err_msg=f"device {shard.device}",
            )
