"""Pipeline-parallelism tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from distributed_pytorch_tpu.models.pipeline_lm import PipelinedTransformerLM
from distributed_pytorch_tpu.parallel.mesh import make_mesh
from distributed_pytorch_tpu.parallel.partitioning import (
    make_param_specs,
    make_state_shardings,
    shard_train_state,
)
from distributed_pytorch_tpu.parallel.pipeline import (
    PIPELINE_STAGE_RULES,
    pipeline_apply,
)
from distributed_pytorch_tpu.parallel.sharding import put_global_batch
from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss
from distributed_pytorch_tpu.training.train_step import (
    create_train_state,
    make_train_step,
)


def test_pipeline_apply_matches_serial_chain():
    """Pipelined execution == sequentially applying the stages."""
    mesh = make_mesh({"stage": 4}, devices=jax.devices()[:4])
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((4, 8, 8)) * 0.3, jnp.float32)
    b = jnp.asarray(rng.standard_normal((4, 8)) * 0.1, jnp.float32)
    x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)

    def stage_fn(params, xin):
        return jnp.tanh(xin @ params["w"] + params["b"])

    out = pipeline_apply(
        stage_fn, {"w": w, "b": b}, x,
        mesh=mesh, num_microbatches=4, data_axis=None,
    )
    expected = x
    for s in range(4):
        expected = stage_fn({"w": w[s], "b": b[s]}, expected)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-5)


@pytest.mark.slow
def test_pipeline_apply_grads_match_serial():
    """Gradients flow back through the ppermute ring and match the serial
    chain's gradients."""
    mesh = make_mesh({"stage": 4}, devices=jax.devices()[:4])
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.standard_normal((4, 6, 6)) * 0.3, jnp.float32)
    x = jnp.asarray(rng.standard_normal((8, 6)), jnp.float32)

    def stage_fn(params, xin):
        return jnp.tanh(xin @ params)

    def piped_loss(w):
        return jnp.sum(
            pipeline_apply(
                stage_fn, w, x, mesh=mesh, num_microbatches=2, data_axis=None
            )
            ** 2
        )

    def serial_loss(w):
        h = x
        for s in range(4):
            h = stage_fn(w[s], h)
        return jnp.sum(h**2)

    g_piped = jax.grad(piped_loss)(w)
    g_serial = jax.grad(serial_loss)(w)
    np.testing.assert_allclose(np.asarray(g_piped), np.asarray(g_serial), atol=1e-4)


@pytest.mark.slow
def test_pipelined_lm_matches_serial_fallback():
    """The same params give the same logits with the pipeline on a stage mesh
    vs the serial chain fallback (mesh=None)."""
    rng = np.random.default_rng(2)
    tokens = rng.integers(0, 32, (8, 12), dtype=np.int32)
    kw = dict(
        vocab_size=32, d_model=16, n_stages=4, layers_per_stage=1,
        n_heads=2, d_ff=32, num_microbatches=2,
    )
    serial = PipelinedTransformerLM(**kw)
    variables = serial.init(jax.random.PRNGKey(0), tokens)
    logits_serial = serial.apply(variables, tokens)

    mesh = make_mesh({"data": 2, "stage": 4})
    piped = PipelinedTransformerLM(**kw, mesh=mesh)
    logits_piped = jax.jit(piped.apply)(variables, jnp.asarray(tokens))
    np.testing.assert_allclose(
        np.asarray(logits_piped), np.asarray(logits_serial), atol=2e-4
    )


@pytest.mark.slow
def test_pp_training_loss_decreases_with_sharded_stages():
    """Full DP x PP train loop: stage params sharded P('stage'), loss falls."""
    mesh = make_mesh({"data": 2, "stage": 4})
    model = PipelinedTransformerLM(
        vocab_size=32, d_model=16, n_stages=4, layers_per_stage=1,
        n_heads=2, d_ff=32, num_microbatches=2, mesh=mesh,
    )
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, 32, (8, 13), dtype=np.int32)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    optimizer = optax.adam(1e-2)
    state = create_train_state(model, optimizer, inputs)
    specs = make_param_specs(state.params, PIPELINE_STAGE_RULES, mesh=mesh)
    stage_leaves = [
        s
        for path, s in jtu.tree_leaves_with_path(
            specs, is_leaf=lambda x: isinstance(x, P)
        )
        if "stages" in str(path)
    ]
    assert stage_leaves and all(s == P("stage") for s in stage_leaves)
    shardings = make_state_shardings(mesh, state, specs)
    state = shard_train_state(state, shardings)
    step = make_train_step(
        model.apply, optimizer, softmax_cross_entropy_loss,
        mesh=mesh, state_sharding=shardings,
    )
    batch = put_global_batch(mesh, (inputs, targets))
    losses = []
    for _ in range(8):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # Stage params are physically distributed.
    stacked = state.params["stages"]
    leaf = jtu.tree_leaves(stacked)[0]
    assert not leaf.sharding.is_fully_replicated
