"""Write-ahead journal tests: record codec, torn-tail quarantine,
segment rotation + compaction, replayed-state folding, the worker
registry, and the randomized kill-point torture drill.

Everything here is stdlib-only — the journal is control-plane plumbing
and must import (and be testable) without JAX. The torture drill is the
property the recovery story leans on: truncate the byte stream at ANY
point, or corrupt any tail, and replay yields a clean prefix of the
appended records with the damage quarantined to ``*.corrupt`` — never an
exception, never a record invented from garbage.
"""

import json
import os
import random

import pytest

from distributed_pytorch_tpu.serving.journal import (
    Journal,
    JournalState,
    decode_record,
    encode_record,
    journal_segments,
    pid_alive,
    read_worker_registry,
    remove_worker_entry,
    replay_journal,
    write_worker_entry,
)

# ------------------------------------------------------------------ codec


def test_record_roundtrip():
    rec = {"k": "submit", "fid": 7, "prompt": [1, 2, 3], "tenant": "t"}
    line = encode_record(rec)
    assert line.endswith(b"\n")
    assert decode_record(line) == rec


def test_decode_rejects_corruption():
    line = encode_record({"k": "cancel", "fid": 3})
    assert decode_record(line) is not None
    # Flip one payload byte: CRC mismatch.
    bad = line[:10] + bytes([line[10] ^ 0x01]) + line[11:]
    assert decode_record(bad) is None
    # Torn writes: any strict prefix (no trailing newline) fails cleanly.
    for cut in (0, 1, 5, 9, len(line) - 1):
        assert decode_record(line[:cut]) is None
    # Garbage that never was a record.
    assert decode_record(b"deadbeef not-json\n") is None


# ---------------------------------------------------------------- replay


def _submit(j, fid, replica="r0"):
    j.append_submit(
        fid,
        prompt=[1, 2, fid],
        params={"max_new_tokens": 4},
        metadata=None,
        tenant="anon",
        mods=None,
        trace_id=f"d{fid:06x}",
        replica=replica,
        req_id=fid,
    )


def test_replay_folds_lifecycle(tmp_path):
    d = str(tmp_path / "j")
    j = Journal(d)
    j.append_replica("spawn", "r0", kind="process", index=0, pid=123)
    _submit(j, 0)
    _submit(j, 1)
    j.append_progress({0: 2, 1: 1})
    j.append_deliver({0: 1})
    j.append_finish(0, [10, 11, 12])
    j.append_cancel(1)
    j.close()

    state = replay_journal(d)
    assert state.corrupt == []
    assert state.replicas["r0"]["alive"] and state.replicas["r0"]["pid"] == 123
    assert state.next_fid == 2
    r0 = state.requests[0]
    assert r0["finished"] and r0["gen"] == [10, 11, 12]
    assert r0["delivered"] == 1
    assert state.requests[1]["cancelled"]
    # Open set: fid 0 is finished but has an undelivered tail; fid 1 is
    # cancelled and drops out.
    assert set(state.open_requests()) == {0}


def test_replica_death_is_final_in_replay(tmp_path):
    d = str(tmp_path / "j")
    j = Journal(d)
    j.append_replica("spawn", "r0", kind="process", index=0, pid=1)
    j.append_replica("dead", "r0", reason="kill_replica_process")
    j.close()
    state = replay_journal(d)
    assert state.replicas["r0"]["alive"] is False
    assert state.replicas["r0"]["reason"] == "kill_replica_process"


def test_rotation_compacts_and_bounds_segments(tmp_path):
    d = str(tmp_path / "j")
    j = Journal(d, segment_max_records=16)
    j.append_replica("spawn", "r0", kind="local", index=0)
    for fid in range(40):
        _submit(j, fid)
        j.append_finish(fid, [7])
        j.append_deliver({fid: 1})  # fully delivered -> compacted away
    assert j.rotations >= 1
    assert j.compacted_away > 0
    # Rotation deletes captured segments: only the live one remains.
    assert len(journal_segments(d)) == 1
    # And replay of the compacted journal still knows the live truth.
    state = replay_journal(d)
    assert state.replicas["r0"]["alive"]
    assert state.open_requests() == {}
    assert state.next_fid == 40
    j.close()


def test_compaction_base_preserves_open_requests(tmp_path):
    d = str(tmp_path / "j")
    j = Journal(d)
    j.append_replica("spawn", "r1", kind="process", index=1, pid=9)
    _submit(j, 5, replica="r1")
    j.append_progress({5: 3})
    j.append_deliver({5: 2})
    j.rotate()
    j.close()
    state = replay_journal(d)
    doc = state.requests[5]
    assert doc["committed"] == 3 and doc["delivered"] == 2
    assert doc["replica"] == "r1" and not doc["finished"]


# ------------------------------------------------------------- torn tails


def test_torn_tail_is_quarantined(tmp_path):
    d = str(tmp_path / "j")
    j = Journal(d)
    _submit(j, 0)
    _submit(j, 1)
    j.close()
    seg = journal_segments(d)[0]
    whole = open(seg, "rb").read()
    # Tear mid-way through the LAST record.
    open(seg, "wb").write(whole[: len(whole) - 4])
    state = replay_journal(d)
    assert 0 in state.requests and 1 not in state.requests
    assert len(state.corrupt) == 1
    quarantined = state.corrupt[0]
    assert quarantined.endswith(".corrupt") and os.path.exists(quarantined)
    # The damaged bytes moved aside, the good prefix stays replayable.
    assert replay_journal(d).corrupt == []
    assert 0 in replay_journal(d).requests


def test_corrupt_middle_record_quarantines_rest_of_segment(tmp_path):
    d = str(tmp_path / "j")
    j = Journal(d)
    for fid in range(3):
        _submit(j, fid)
    j.close()
    seg = journal_segments(d)[0]
    lines = open(seg, "rb").read().splitlines(keepends=True)
    # Corrupt the middle submit's CRC: everything after the last good
    # record is suspect and quarantined with it.
    lines[2] = b"00000000" + lines[2][8:]
    open(seg, "wb").write(b"".join(lines))
    state = replay_journal(d)
    assert 0 in state.requests
    assert 1 not in state.requests and 2 not in state.requests
    assert len(state.corrupt) == 1


def test_quarantine_names_never_collide(tmp_path):
    d = str(tmp_path / "j")
    for round_ in range(3):
        j = Journal(d)
        _submit(j, round_)
        j.close()
        seg = journal_segments(d)[-1]
        with open(seg, "ab") as f:
            f.write(b"garbage tail\n")
        replay_journal(d)
    corrupts = [p for p in os.listdir(d) if ".corrupt" in p]
    assert len(corrupts) == 3
    assert len(set(corrupts)) == 3


# ------------------------------------------------- kill-point torture drill


def _apply_script(j, script):
    """Replay a deterministic op script into a journal; returns the op
    count actually journaled."""
    for op in script:
        kind = op[0]
        if kind == "submit":
            _submit(j, op[1])
        elif kind == "progress":
            j.append_progress({op[1]: op[2]})
        elif kind == "deliver":
            j.append_deliver({op[1]: op[2]})
        elif kind == "finish":
            j.append_finish(op[1], list(range(op[2])))
        elif kind == "cancel":
            j.append_cancel(op[1])


def _make_script(rng, n_ops):
    script = []
    fid = 0
    live = []
    for _ in range(n_ops):
        roll = rng.random()
        if roll < 0.35 or not live:
            script.append(("submit", fid))
            live.append(fid)
            fid += 1
        elif roll < 0.55:
            script.append(("progress", rng.choice(live), rng.randint(1, 6)))
        elif roll < 0.75:
            script.append(("deliver", rng.choice(live), rng.randint(1, 6)))
        elif roll < 0.9:
            victim = live.pop(rng.randrange(len(live)))
            script.append(("finish", victim, rng.randint(1, 6)))
        else:
            victim = live.pop(rng.randrange(len(live)))
            script.append(("cancel", victim))
    return script


@pytest.mark.parametrize("seed", range(8))
def test_truncation_torture_replays_clean_prefix(tmp_path, seed):
    """SIGKILL model: the journal byte stream may stop ANYWHERE. For
    randomized op scripts and randomized kill offsets, replay must (a)
    never raise, (b) produce exactly the fold of some record prefix, and
    (c) quarantine at most one tail."""
    rng = random.Random(seed)
    d = str(tmp_path / f"j{seed}")
    j = Journal(d, segment_max_records=64)
    script = _make_script(rng, 60)
    _apply_script(j, script)
    j.close()

    seg = journal_segments(d)[-1]
    whole = open(seg, "rb").read()
    lines = whole.splitlines(keepends=True)
    # Reference folds: state after each whole-record prefix of the
    # surviving segment (earlier segments were compacted into its base).
    folds = []
    st = JournalState()
    folds.append({fid: dict(doc) for fid, doc in st.requests.items()})
    for line in lines:
        rec = decode_record(line)
        assert rec is not None
        st.apply(rec)
        folds.append({fid: dict(doc) for fid, doc in st.requests.items()})

    for _ in range(6):
        cut = rng.randrange(len(whole) + 1)
        open(seg, "wb").write(whole[:cut])
        state = replay_journal(d)
        got = {fid: dict(doc) for fid, doc in state.requests.items()}
        assert got in folds, f"cut at {cut}: not a prefix fold"
        assert len(state.corrupt) <= 1
        # Restore the pristine segment (quarantine moved the tail off).
        for leftover in os.listdir(d):
            if ".corrupt" in leftover:
                os.unlink(os.path.join(d, leftover))
        open(seg, "wb").write(whole)


def test_recovery_journal_survives_its_own_kill(tmp_path):
    """The compaction-base write itself can be torn: a journal opened
    with a recovered state must leave the directory replayable at every
    byte prefix of its base segment."""
    d = str(tmp_path / "j")
    j = Journal(d)
    j.append_replica("spawn", "r0", kind="process", index=0, pid=44)
    _submit(j, 0)
    j.append_progress({0: 2})
    j.close()
    state = replay_journal(d)
    j2 = Journal(d, state=state)  # compacts, unlinks the old segment
    j2.close()
    seg = journal_segments(d)[-1]
    whole = open(seg, "rb").read()
    for cut in range(0, len(whole) + 1, 7):
        open(seg, "wb").write(whole[:cut])
        replay_journal(d)  # must never raise
        for leftover in os.listdir(d):
            if ".corrupt" in leftover:
                os.unlink(os.path.join(d, leftover))
    open(seg, "wb").write(whole)
    final = replay_journal(d)
    assert final.requests[0]["committed"] == 2
    assert final.replicas["r0"]["alive"]


# --------------------------------------------------------- worker registry


def test_worker_registry_roundtrip(tmp_path):
    run = str(tmp_path)
    write_worker_entry(run, {
        "name": "r0", "pid": os.getpid(), "control_url": "http://x",
        "fingerprint": "abc", "spec": {"name": "r0"},
    })
    write_worker_entry(run, {"name": "r1", "pid": 1, "control_url": None})
    reg = read_worker_registry(run)
    assert set(reg) == {"r0", "r1"}
    assert reg["r0"]["pid"] == os.getpid()
    remove_worker_entry(run, "r0")
    assert set(read_worker_registry(run)) == {"r1"}
    # Unreadable entries are skipped, not fatal.
    junk = os.path.join(run, "workers", "r2.json")
    open(junk, "w").write("{not json")
    assert set(read_worker_registry(run)) == {"r1"}


def test_pid_alive():
    assert pid_alive(os.getpid())
    assert not pid_alive(None)
    # Allocate-and-reap a child so the pid is known-dead.
    pid = os.fork()
    if pid == 0:
        os._exit(0)
    os.waitpid(pid, 0)
    assert not pid_alive(pid)


def test_registry_entry_is_json_on_disk(tmp_path):
    run = str(tmp_path)
    write_worker_entry(run, {"name": "r9", "pid": 7})
    path = os.path.join(run, "workers", "r9.json")
    doc = json.load(open(path))
    assert doc["name"] == "r9" and doc["pid"] == 7
