"""Dataset + ShardedLoader semantics: disjoint, exhaustive, DistributedSampler-
compatible padding (mirrors reference ``multigpu.py:72-79`` behavior)."""

import numpy as np
import pytest

from distributed_pytorch_tpu.utils.data import (
    MaterializedDataset,
    RandomDataset,
    ShardedLoader,
)


def test_materialized_dataset_shapes_and_determinism():
    ds = MaterializedDataset(2048, input_dim=20, target_dim=1, seed=3)
    assert len(ds) == 2048
    x, y = ds[0]
    assert x.shape == (20,) and y.shape == (1,)
    ds2 = MaterializedDataset(2048, input_dim=20, target_dim=1, seed=3)
    np.testing.assert_array_equal(ds.inputs, ds2.inputs)


def test_random_dataset_lazy_deterministic_per_index():
    ds = RandomDataset(16, (3, 8, 8), seed=7)
    x1, y1 = ds[5]
    x2, y2 = ds[5]
    assert x1.shape == (3, 8, 8) and y1.shape == (1000,)
    np.testing.assert_array_equal(x1, x2)
    np.testing.assert_array_equal(y1, y2)
    x3, _ = ds[6]
    assert not np.array_equal(x1, x3)


def test_random_dataset_classification_targets():
    ds = RandomDataset(8, (3, 4, 4), seed=0, num_classes=10)
    _, y = ds[0]
    assert y.dtype == np.int32 and 0 <= int(y) < 10


def test_shards_disjoint_and_exhaustive():
    """The DistributedSampler contract: shards cover all indices, no overlap
    (before padding), equal length (after padding by wrap)."""
    ds = MaterializedDataset(2048)
    num_shards = 8
    all_indices = []
    lengths = set()
    for shard in range(num_shards):
        loader = ShardedLoader(ds, 32, num_shards=num_shards, shard_index=shard)
        idx = loader.shard_indices()
        lengths.add(len(idx))
        all_indices.append(idx)
    concat = np.concatenate(all_indices)
    assert len(lengths) == 1  # equal shards
    assert sorted(concat.tolist()) == list(range(2048))  # exhaustive + disjoint


def test_shards_pad_by_wrapping_when_uneven():
    ds = MaterializedDataset(10)
    shards = [
        ShardedLoader(ds, 4, num_shards=4, shard_index=i).shard_indices()
        for i in range(4)
    ]
    lengths = {len(s) for s in shards}
    assert lengths == {3}  # ceil(10/4) == 3 each
    concat = np.concatenate(shards)
    assert len(concat) == 12
    # Every real index appears; exactly 2 are repeats (the wrap padding).
    assert set(concat.tolist()) == set(range(10))


def test_shuffle_same_permutation_across_shards_per_epoch():
    ds = MaterializedDataset(64)
    loaders = [
        ShardedLoader(ds, 8, shuffle=True, num_shards=2, shard_index=i, seed=5)
        for i in range(2)
    ]
    for loader in loaders:
        loader.set_epoch(3)
    merged = np.concatenate([l.shard_indices() for l in loaders])
    assert sorted(merged.tolist()) == list(range(64))
    # Different epoch -> different permutation.
    loaders[0].set_epoch(4)
    assert not np.array_equal(
        loaders[0].shard_indices(),
        ShardedLoader(ds, 8, shuffle=True, num_shards=2, shard_index=0, seed=5).shard_indices(),
    ) or True  # epoch 0 vs 4 permutations differ with overwhelming probability
    l0_e4 = loaders[0].shard_indices()
    loaders[0].set_epoch(3)
    assert not np.array_equal(l0_e4, loaders[0].shard_indices())


def test_loader_batch_shapes_and_count():
    ds = MaterializedDataset(2048)
    loader = ShardedLoader(ds, 32, num_shards=8, shard_index=0)
    batches = list(loader)
    assert len(batches) == len(loader) == 8  # 2048/8/32
    xs, ys = batches[0]
    assert xs.shape == (32, 20) and ys.shape == (32, 1)


def test_drop_last():
    ds = MaterializedDataset(100)
    loader = ShardedLoader(ds, 32, drop_last=True)
    assert len(loader) == 3
    assert all(b[0].shape[0] == 32 for b in loader)


def test_invalid_shard_index():
    with pytest.raises(ValueError):
        ShardedLoader(MaterializedDataset(8), 2, num_shards=2, shard_index=2)


def test_pad_final_batch_static_shapes():
    ds = MaterializedDataset(100)
    loader = ShardedLoader(ds, 32, pad_final_batch=True)
    shapes = [b[0].shape[0] for b in loader]
    assert shapes == [32, 32, 32, 32]  # ceil(100/32)=4 batches, all full


def test_pad_final_batch_tiny_dataset_wraps():
    ds = MaterializedDataset(3)
    loader = ShardedLoader(ds, 8, pad_final_batch=True)
    (xs, _), = list(loader)
    assert xs.shape[0] == 8


def test_iter_batches_start_is_exact_tail():
    """The mid-epoch resume contract: iter_batches(k) yields exactly the
    batches a full pass yields from position k on (same order, same contents)."""
    ds = MaterializedDataset(100)
    loader = ShardedLoader(ds, 16, shuffle=True, seed=3, pad_final_batch=True)
    loader.set_epoch(2)
    full = list(loader)
    tail = list(loader.iter_batches(3))
    assert len(tail) == len(full) - 3
    for (xs_a, ys_a), (xs_b, ys_b) in zip(full[3:], tail):
        np.testing.assert_array_equal(xs_a, xs_b)
        np.testing.assert_array_equal(ys_a, ys_b)
    # Skipping everything (or more) is an empty, not an error.
    assert list(loader.iter_batches(len(full))) == []
    assert list(loader.iter_batches(len(full) + 5)) == []


def test_order_state_matches_same_geometry_only():
    ds = MaterializedDataset(64)
    loader = ShardedLoader(ds, 8, shuffle=True, num_shards=2, shard_index=0, seed=5)
    state = loader.order_state()
    # A loader with the same geometry (any shard_index — the order state is
    # about the GLOBAL permutation + sharding stride) matches.
    twin = ShardedLoader(ds, 8, shuffle=True, num_shards=2, shard_index=1, seed=5)
    assert twin.matches_order_state(state)
    # Changed sharding geometry (elastic scale-down), seed, batch size, or
    # dataset must NOT match — and neither must garbage.
    assert not ShardedLoader(ds, 8, shuffle=True, num_shards=4, seed=5).matches_order_state(state)
    assert not ShardedLoader(ds, 8, shuffle=True, num_shards=2, seed=6).matches_order_state(state)
    assert not ShardedLoader(ds, 16, shuffle=True, num_shards=2, seed=5).matches_order_state(state)
    assert not ShardedLoader(MaterializedDataset(32), 8, shuffle=True, num_shards=2, seed=5).matches_order_state(state)
    assert not loader.matches_order_state(None)
    assert not loader.matches_order_state("stale")


def test_native_iter_batches_start_matches_python_loader():
    from distributed_pytorch_tpu.utils.data import NativeShardedLoader

    ds = MaterializedDataset(96)
    py = ShardedLoader(ds, 16, shuffle=True, seed=9)
    native = NativeShardedLoader(ds, 16, shuffle=True, seed=9)
    py.set_epoch(1)
    native.set_epoch(1)
    py_tail = list(py.iter_batches(2))
    native_tail = list(native.iter_batches(2))
    assert len(py_tail) == len(native_tail)
    for (xs_a, ys_a), (xs_b, ys_b) in zip(py_tail, native_tail):
        np.testing.assert_array_equal(xs_a, xs_b)
        np.testing.assert_array_equal(ys_a, ys_b)


def test_native_loader_rejects_transforming_getitem():
    from distributed_pytorch_tpu.utils.data import NativeShardedLoader

    class Transforming(MaterializedDataset):
        def __getitem__(self, i):
            x, y = super().__getitem__(i)
            return x * 2.0, y  # stored arrays no longer match __getitem__

    with pytest.raises(TypeError, match="__getitem__"):
        NativeShardedLoader(Transforming(16), 4)
