"""bench.py's evidence-chain hardening (round-4 VERDICT item 1).

The driver's entire perf record for a round is one stdout JSON line from
``bench.py``; round 3 lost its record to a wedged TPU tunnel that turned
backend init into first a traceback and later an eternal zero-CPU hang.
These tests pin the failure path: bounded watchdogged init, and a single
parseable JSON line for every failure mode.
"""

import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import bench  # noqa: E402


class TestEmitFailure:
    def _capture(self, capsys, **kw):
        bench.emit_failure(**kw)
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 1, out
        return json.loads(out[0])

    def test_single_parseable_line_with_cause(self, capsys):
        row = self._capture(
            capsys,
            error="backend_unavailable",
            detail="RuntimeError: tunnel down\nmore context",
            stage="init",
        )
        assert row["error"] == "backend_unavailable"
        assert row["stage"] == "init"
        assert row["value"] is None
        assert row["vs_baseline"] is None
        assert "more context" in row["detail"]  # last line of the detail

    def test_metric_name_follows_mode(self, capsys):
        row = self._capture(
            capsys,
            error="bench_failed",
            detail="x",
            stage="measure",
            metric="dp_weak_scaling_efficiency",
            unit="ratio_vs_1dev",
        )
        assert row["metric"] == "dp_weak_scaling_efficiency"
        assert row["unit"] == "ratio_vs_1dev"

    def test_detail_truncated(self, capsys):
        row = self._capture(
            capsys, error="e", detail="y" * 10_000, stage="measure"
        )
        assert len(row["detail"]) <= 400


class TestInitBackendRetry:
    def test_hang_is_bounded_by_watchdog(self, monkeypatch):
        """A backend init that never returns (the observed wedged-tunnel
        mode) must convert into a failure within ~attempt_timeout, not
        stall the driver forever."""
        import jax

        monkeypatch.setattr(
            jax, "devices", lambda *a: time.sleep(3600), raising=True
        )
        t0 = time.monotonic()
        dev, err = bench.init_backend_with_retry(
            retries=3, base_delay=0.01, attempt_timeout=0.5
        )
        elapsed = time.monotonic() - t0
        assert dev is None
        assert "hung" in err
        # One watchdog window, no retries (a fresh dial would joins the same
        # wedged relay), plus slack.
        assert elapsed < 5.0, elapsed

    def test_exception_retries_then_reports(self, monkeypatch):
        import jax

        calls = []

        def boom():
            calls.append(1)
            raise RuntimeError("UNAVAILABLE: no backend")

        monkeypatch.setattr(jax, "devices", boom, raising=True)
        dev, err = bench.init_backend_with_retry(
            retries=3, base_delay=0.01, attempt_timeout=5.0
        )
        assert dev is None
        assert "UNAVAILABLE" in err
        assert len(calls) == 3  # bounded retries, then structured failure

    def test_success_passes_through(self):
        dev, err = bench.init_backend_with_retry(retries=1)
        assert err is None
        assert dev is not None  # the test rig's CPU backend


def test_peak_flops_table():
    class FakeDev:
        def __init__(self, kind):
            self.device_kind = kind

    assert bench.peak_flops_per_chip(FakeDev("TPU v5 lite")) == 197e12
    assert bench.peak_flops_per_chip(FakeDev("TPU v4")) == 275e12
    # Unknown chips get the conservative default, never a flattering guess.
    assert bench.peak_flops_per_chip(FakeDev("TPU v99")) == bench.DEFAULT_PEAK
