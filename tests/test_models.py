"""Model-tier tests: shapes, BatchNorm state plumbing, trainability."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_pytorch_tpu.models import MLP, ResNet18, ResNet50, ToyRegressor
from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss
from distributed_pytorch_tpu.training.train_step import (
    create_train_state,
    make_train_step,
)


def test_toy_and_mlp_shapes():
    x = np.zeros((8, 20), np.float32)
    for model, out in [(ToyRegressor(), 1), (MLP(hidden=(32,), features=5), 5)]:
        variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x))
        y = model.apply(variables, jnp.asarray(x))
        assert y.shape == (8, out)


@pytest.mark.slow
def test_resnet18_forward_and_param_count():
    model = ResNet18(num_classes=10)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x)
    assert "batch_stats" in variables
    y, updates = model.apply(variables, x, mutable=["batch_stats"])
    assert y.shape == (2, 10)
    assert "batch_stats" in updates


@pytest.mark.slow
def test_resnet50_param_count_matches_torchvision():
    """~25.5M params — sanity anchor against the reference's torchvision model
    (multigpu_profile.py:23)."""
    model = ResNet50(num_classes=1000)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(variables["params"]))
    assert 25.4e6 < n_params < 25.7e6, n_params


@pytest.mark.slow
def test_resnet_trains_with_batch_stats():
    """End-to-end step on a BN model: loss finite, batch_stats actually move."""
    model = ResNet18(num_classes=10)
    opt = optax.sgd(1e-2, momentum=0.9)
    x = np.random.default_rng(0).standard_normal((8, 32, 32, 3)).astype(np.float32)
    y = np.arange(8, dtype=np.int32) % 10
    state = create_train_state(model, opt, x)
    before = jax.tree_util.tree_leaves(state.model_state)[0].copy()
    step = make_train_step(model.apply, opt, softmax_cross_entropy_loss)
    state, loss = step(state, (jnp.asarray(x), jnp.asarray(y)))
    assert np.isfinite(float(loss))
    assert int(state.step) == 1
    after = jax.tree_util.tree_leaves(state.model_state)[0]
    assert not np.allclose(np.asarray(before), np.asarray(after))
