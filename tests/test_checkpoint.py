"""Checkpoint/snapshot round-trip tests (mirror of the reference's
snapshot contract, ``multigpu_torchrun.py:36-40,57-62``)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_pytorch_tpu.checkpoint import (
    load_checkpoint,
    load_snapshot,
    save_checkpoint,
    save_snapshot,
)
from distributed_pytorch_tpu.models.toy import ToyRegressor
from distributed_pytorch_tpu.training.train_step import create_train_state


def _state(seed=0):
    model = ToyRegressor()
    opt = optax.adam(1e-3)  # adam: nontrivial opt_state, exercises the fidelity gap
    x = np.zeros((4, 20), np.float32)
    return create_train_state(model, opt, x, rng_seed=seed)


def test_checkpoint_roundtrip(tmp_path):
    state = _state()
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, state.params, metadata={"epoch": 3})
    restored, meta = load_checkpoint(path, state.params)
    assert meta["epoch"] == 3
    for a, b in zip(jax.tree_util.tree_leaves(state.params), jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_snapshot_roundtrip_includes_opt_state_and_epoch(tmp_path):
    state = _state(seed=1)
    path = str(tmp_path / "snapshot.npz")
    save_snapshot(path, state, epochs_run=7)
    template = _state(seed=2)  # different values, same structure
    restored, epochs_run = load_snapshot(path, template)
    assert epochs_run == 7
    for a, b in zip(jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_atomic_write_no_partial_file(tmp_path):
    state = _state()
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, state.params)
    # No stray tmp files left behind.
    assert [f for f in os.listdir(tmp_path) if f.endswith(".tmp.npz")] == []


def test_template_structure_mismatch_raises(tmp_path):
    state = _state()
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, state.params)
    bad_template = {"totally": jnp.zeros((2,))}
    with pytest.raises(KeyError):
        load_checkpoint(path, bad_template)


def test_shape_mismatch_raises(tmp_path):
    state = _state()
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, state.params)
    bigger = jax.tree_util.tree_map(
        lambda x: np.zeros(tuple(d + 1 for d in x.shape), x.dtype), state.params
    )
    with pytest.raises(ValueError):
        load_checkpoint(path, bigger)
