"""Checkpoint/snapshot round-trip tests (mirror of the reference's
snapshot contract, ``multigpu_torchrun.py:36-40,57-62``)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_pytorch_tpu.checkpoint import (
    load_checkpoint,
    load_snapshot,
    save_checkpoint,
    save_snapshot,
)
from distributed_pytorch_tpu.models.toy import ToyRegressor
from distributed_pytorch_tpu.training.train_step import create_train_state


def _state(seed=0):
    model = ToyRegressor()
    opt = optax.adam(1e-3)  # adam: nontrivial opt_state, exercises the fidelity gap
    x = np.zeros((4, 20), np.float32)
    return create_train_state(model, opt, x, rng_seed=seed)


def test_checkpoint_roundtrip(tmp_path):
    state = _state()
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, state.params, metadata={"epoch": 3})
    restored, meta = load_checkpoint(path, state.params)
    assert meta["epoch"] == 3
    for a, b in zip(jax.tree_util.tree_leaves(state.params), jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_snapshot_roundtrip_includes_opt_state_and_epoch(tmp_path):
    state = _state(seed=1)
    path = str(tmp_path / "snapshot.npz")
    save_snapshot(path, state, epochs_run=7)
    template = _state(seed=2)  # different values, same structure
    restored, epochs_run = load_snapshot(path, template)
    assert epochs_run == 7
    for a, b in zip(jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_atomic_write_no_partial_file(tmp_path):
    state = _state()
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, state.params)
    # No stray tmp files left behind.
    assert [f for f in os.listdir(tmp_path) if ".tmp" in f] == []


def test_template_structure_mismatch_raises(tmp_path):
    state = _state()
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, state.params)
    bad_template = {"totally": jnp.zeros((2,))}
    with pytest.raises(KeyError):
        load_checkpoint(path, bad_template)


def test_shape_mismatch_raises(tmp_path):
    state = _state()
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, state.params)
    bigger = jax.tree_util.tree_map(
        lambda x: np.zeros(tuple(d + 1 for d in x.shape), x.dtype), state.params
    )
    with pytest.raises(ValueError):
        load_checkpoint(path, bigger)


class TestOrbaxInterop:
    @pytest.fixture(autouse=True)
    def _require_orbax(self):
        pytest.importorskip("orbax.checkpoint")

    def test_roundtrip_trainstate(self, tmp_path):
        import jax.numpy as jnp
        import optax

        from distributed_pytorch_tpu.checkpoint import (
            export_orbax,
            import_orbax,
        )
        from distributed_pytorch_tpu.models import ToyRegressor
        from distributed_pytorch_tpu.training.train_step import (
            create_train_state,
            make_train_step,
        )
        from distributed_pytorch_tpu.training.losses import mse_loss

        rng = np.random.default_rng(0)
        xs = jnp.asarray(rng.standard_normal((32, 20)), jnp.float32)
        ys = jnp.asarray(rng.standard_normal((32, 1)), jnp.float32)
        opt = optax.adam(1e-2)
        state = create_train_state(ToyRegressor(), opt, xs)
        step = make_train_step(ToyRegressor().apply, opt, mse_loss)
        state, _ = step(state, (xs, ys))

        path = str(tmp_path / "orbax_ckpt")
        export_orbax(path, state, epochs_run=5)
        template = create_train_state(ToyRegressor(), opt, xs)
        restored, epochs = import_orbax(path, template)
        assert epochs == 5
        for a, b in zip(
            jax.tree_util.tree_leaves(state),
            jax.tree_util.tree_leaves(restored),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6
            )

    def test_missing_metadata_defaults_to_zero(self, tmp_path):
        import jax.numpy as jnp

        from distributed_pytorch_tpu.checkpoint import (
            export_orbax,
            import_orbax,
        )

        tree = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
        path = str(tmp_path / "bare")
        export_orbax(path, tree)
        os.unlink(path + ".meta.json")
        restored, epochs = import_orbax(path, tree)
        assert epochs == 0
        np.testing.assert_array_equal(np.asarray(restored["w"]), 1.0)


class TestOrbaxShardedRestore:
    """import_orbax(shardings=): leaves come back as jax.Arrays with the
    requested placement (each host reads only its addressable shards) — the
    restore-side mirror of the sharded-native export."""

    def test_roundtrip_preserves_sharding_and_values(self, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        import warnings

        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_pytorch_tpu.checkpoint import (
            export_orbax,
            import_orbax,
        )
        from distributed_pytorch_tpu.parallel.mesh import make_mesh

        mesh = make_mesh({"data": 8})
        sharded = NamedSharding(mesh, P("data"))
        replicated = NamedSharding(mesh, P())
        tree = {
            "w": jax.device_put(jnp.arange(32.0), sharded),
            "b": jax.device_put(jnp.ones((3,)), replicated),
        }
        path = str(tmp_path / "orbax_sharded")
        export_orbax(path, tree)

        template = {
            "w": jax.ShapeDtypeStruct((32,), jnp.float32),
            "b": jax.ShapeDtypeStruct((3,), jnp.float32),
        }
        shardings = {"w": sharded, "b": replicated}
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            restored, _ = import_orbax(path, template, shardings=shardings)
        # The sharded path supplies placements up front — no "populating
        # sharding info from file" slow-path warning.
        assert not any("Sharding info" in str(w.message) for w in caught)
        assert restored["w"].sharding.is_equivalent_to(sharded, 1)
        assert restored["b"].sharding.is_equivalent_to(replicated, 1)
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.arange(32.0)
        )
        np.testing.assert_array_equal(np.asarray(restored["b"]), np.ones(3))

        # dtype cast follows the template (shared alignment contract) while
        # the placement survives the cast.
        bf16_template = {
            "w": jax.ShapeDtypeStruct((32,), jnp.bfloat16),
            "b": jax.ShapeDtypeStruct((3,), jnp.bfloat16),
        }
        cast, _ = import_orbax(path, bf16_template, shardings=shardings)
        assert cast["w"].dtype == jnp.bfloat16
        assert cast["w"].sharding.is_equivalent_to(sharded, 1)

    def test_shape_mismatch_fails_loudly(self, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_pytorch_tpu.checkpoint import (
            export_orbax,
            import_orbax,
        )
        from distributed_pytorch_tpu.parallel.mesh import make_mesh

        mesh = make_mesh({"data": 8})
        rep = NamedSharding(mesh, P())
        tree = {"w": jax.device_put(jnp.ones((8,)), rep)}
        path = str(tmp_path / "orbax_badshape")
        export_orbax(path, tree)
        template = {"w": jax.ShapeDtypeStruct((9,), jnp.float32)}
        with pytest.raises(ValueError, match="shape"):
            import_orbax(path, template, shardings={"w": rep})
