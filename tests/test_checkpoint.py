"""Checkpoint/snapshot round-trip tests (mirror of the reference's
snapshot contract, ``multigpu_torchrun.py:36-40,57-62``)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_pytorch_tpu.checkpoint import (
    load_checkpoint,
    load_snapshot,
    save_checkpoint,
    save_snapshot,
)
from distributed_pytorch_tpu.models.toy import ToyRegressor
from distributed_pytorch_tpu.training.train_step import create_train_state


def _state(seed=0):
    model = ToyRegressor()
    opt = optax.adam(1e-3)  # adam: nontrivial opt_state, exercises the fidelity gap
    x = np.zeros((4, 20), np.float32)
    return create_train_state(model, opt, x, rng_seed=seed)


def test_checkpoint_roundtrip(tmp_path):
    state = _state()
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, state.params, metadata={"epoch": 3})
    restored, meta = load_checkpoint(path, state.params)
    assert meta["epoch"] == 3
    for a, b in zip(jax.tree_util.tree_leaves(state.params), jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_snapshot_roundtrip_includes_opt_state_and_epoch(tmp_path):
    state = _state(seed=1)
    path = str(tmp_path / "snapshot.npz")
    save_snapshot(path, state, epochs_run=7)
    template = _state(seed=2)  # different values, same structure
    restored, meta = load_snapshot(path, template)
    assert meta["epochs_run"] == 7
    for a, b in zip(jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_snapshot_step_in_epoch_and_extra_meta_roundtrip(tmp_path):
    """The drain snapshot schema: step_in_epoch + arbitrary extra metadata
    (loader order state, carried loss sums) survive the npz round trip."""
    state = _state(seed=1)
    path = str(tmp_path / "snapshot.npz")
    order = {"seed": 0, "shuffle": True, "num_shards": 2,
             "batch_size": 32, "dataset_size": 256}
    save_snapshot(
        path, state, epochs_run=2, step_in_epoch=5,
        extra_meta={"order": order, "loss_sum": 1.25, "loss_count": 5},
    )
    restored, meta = load_snapshot(path, _state(seed=2))
    assert meta["epochs_run"] == 2
    assert meta["step_in_epoch"] == 5
    assert meta["order"] == order
    assert meta["loss_sum"] == 1.25 and meta["loss_count"] == 5
    for a, b in zip(jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_old_snapshot_without_step_meta_defaults_to_zero(tmp_path):
    """Pre-drain snapshots (no step_in_epoch key) load with step 0 — backward
    compatibility for checkpoints written before this schema existed."""
    import json

    state = _state(seed=1)
    path = str(tmp_path / "snapshot.npz")
    save_snapshot(path, state, epochs_run=3)
    # Rewrite the metadata entry without the new key, simulating an old file.
    # Array bytes are untouched, so the embedded integrity manifest still holds.
    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files}
    meta = json.loads(bytes(arrays["__checkpoint_meta__"].tobytes()).decode("utf-8"))
    meta.pop("step_in_epoch")
    arrays["__checkpoint_meta__"] = np.frombuffer(
        json.dumps(meta).encode("utf-8"), dtype=np.uint8
    )
    np.savez(path, **arrays)
    restored, loaded = load_snapshot(path, _state(seed=2))
    assert loaded["epochs_run"] == 3
    assert loaded["step_in_epoch"] == 0


def test_atomic_write_no_partial_file(tmp_path):
    state = _state()
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, state.params)
    # No stray tmp files left behind.
    assert [f for f in os.listdir(tmp_path) if ".tmp" in f] == []


def test_template_structure_mismatch_raises(tmp_path):
    state = _state()
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, state.params)
    bad_template = {"totally": jnp.zeros((2,))}
    with pytest.raises(KeyError):
        load_checkpoint(path, bad_template)


def test_shape_mismatch_raises(tmp_path):
    state = _state()
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, state.params)
    bigger = jax.tree_util.tree_map(
        lambda x: np.zeros(tuple(d + 1 for d in x.shape), x.dtype), state.params
    )
    with pytest.raises(ValueError):
        load_checkpoint(path, bigger)


class TestOrbaxInterop:
    @pytest.fixture(autouse=True)
    def _require_orbax(self):
        pytest.importorskip("orbax.checkpoint")

    def test_roundtrip_trainstate(self, tmp_path):
        import jax.numpy as jnp
        import optax

        from distributed_pytorch_tpu.checkpoint import (
            export_orbax,
            import_orbax,
        )
        from distributed_pytorch_tpu.models import ToyRegressor
        from distributed_pytorch_tpu.training.train_step import (
            create_train_state,
            make_train_step,
        )
        from distributed_pytorch_tpu.training.losses import mse_loss

        rng = np.random.default_rng(0)
        xs = jnp.asarray(rng.standard_normal((32, 20)), jnp.float32)
        ys = jnp.asarray(rng.standard_normal((32, 1)), jnp.float32)
        opt = optax.adam(1e-2)
        state = create_train_state(ToyRegressor(), opt, xs)
        step = make_train_step(ToyRegressor().apply, opt, mse_loss)
        state, _ = step(state, (xs, ys))

        path = str(tmp_path / "orbax_ckpt")
        export_orbax(path, state, epochs_run=5)
        template = create_train_state(ToyRegressor(), opt, xs)
        restored, epochs = import_orbax(path, template)
        assert epochs == 5
        for a, b in zip(
            jax.tree_util.tree_leaves(state),
            jax.tree_util.tree_leaves(restored),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6
            )

    def test_missing_metadata_defaults_to_zero(self, tmp_path):
        import jax.numpy as jnp

        from distributed_pytorch_tpu.checkpoint import (
            export_orbax,
            import_orbax,
        )

        tree = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
        path = str(tmp_path / "bare")
        export_orbax(path, tree)
        os.unlink(path + ".meta.json")
        restored, epochs = import_orbax(path, tree)
        assert epochs == 0
        np.testing.assert_array_equal(np.asarray(restored["w"]), 1.0)


class TestOrbaxShardedRestore:
    """import_orbax(shardings=): leaves come back as jax.Arrays with the
    requested placement (each host reads only its addressable shards) — the
    restore-side mirror of the sharded-native export."""

    def test_roundtrip_preserves_sharding_and_values(self, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        import warnings

        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_pytorch_tpu.checkpoint import (
            export_orbax,
            import_orbax,
        )
        from distributed_pytorch_tpu.parallel.mesh import make_mesh

        mesh = make_mesh({"data": 8})
        sharded = NamedSharding(mesh, P("data"))
        replicated = NamedSharding(mesh, P())
        tree = {
            "w": jax.device_put(jnp.arange(32.0), sharded),
            "b": jax.device_put(jnp.ones((3,)), replicated),
        }
        path = str(tmp_path / "orbax_sharded")
        export_orbax(path, tree)

        template = {
            "w": jax.ShapeDtypeStruct((32,), jnp.float32),
            "b": jax.ShapeDtypeStruct((3,), jnp.float32),
        }
        shardings = {"w": sharded, "b": replicated}
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            restored, _ = import_orbax(path, template, shardings=shardings)
        # The sharded path supplies placements up front — no "populating
        # sharding info from file" slow-path warning.
        assert not any("Sharding info" in str(w.message) for w in caught)
        assert restored["w"].sharding.is_equivalent_to(sharded, 1)
        assert restored["b"].sharding.is_equivalent_to(replicated, 1)
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.arange(32.0)
        )
        np.testing.assert_array_equal(np.asarray(restored["b"]), np.ones(3))

        # dtype cast follows the template (shared alignment contract) while
        # the placement survives the cast.
        bf16_template = {
            "w": jax.ShapeDtypeStruct((32,), jnp.bfloat16),
            "b": jax.ShapeDtypeStruct((3,), jnp.bfloat16),
        }
        cast, _ = import_orbax(path, bf16_template, shardings=shardings)
        assert cast["w"].dtype == jnp.bfloat16
        assert cast["w"].sharding.is_equivalent_to(sharded, 1)

    def test_shape_mismatch_fails_loudly(self, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_pytorch_tpu.checkpoint import (
            export_orbax,
            import_orbax,
        )
        from distributed_pytorch_tpu.parallel.mesh import make_mesh

        mesh = make_mesh({"data": 8})
        rep = NamedSharding(mesh, P())
        tree = {"w": jax.device_put(jnp.ones((8,)), rep)}
        path = str(tmp_path / "orbax_badshape")
        export_orbax(path, tree)
        template = {"w": jax.ShapeDtypeStruct((9,), jnp.float32)}
        with pytest.raises(ValueError, match="shape"):
            import_orbax(path, template, shardings={"w": rep})


class TestCheckpointManager:
    """Rotation: newest `keep` survive, the best-metric file is protected,
    the directory is self-describing across manager instances."""

    def _state(self, seed):
        return {"w": jnp.full((4,), float(seed))}

    def test_keeps_last_k_and_best(self, tmp_path):
        from distributed_pytorch_tpu.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "ckpts"), keep=2, mode="min")
        # Step 1 has the BEST (lowest) metric; later steps are worse.
        for step, metric in [(1, 0.1), (2, 0.5), (3, 0.4), (4, 0.3)]:
            mgr.save(self._state(step), step=step, metric=metric)
        names = sorted(
            p.name for p in (tmp_path / "ckpts").glob("ckpt_*.npz")
        )
        # keep=2 -> steps 3, 4; step 1 survives as best; step 2 pruned.
        assert names == [
            "ckpt_0000000001.npz",
            "ckpt_0000000003.npz",
            "ckpt_0000000004.npz",
        ]

    def test_restore_latest_and_best(self, tmp_path):
        from distributed_pytorch_tpu.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "c"), keep=2)
        for step, metric in [(1, 0.9), (2, 0.2), (3, 0.7)]:
            mgr.save(self._state(step), step=step, metric=metric)
        template = {"w": jnp.zeros((4,))}
        latest, meta = mgr.restore(template)
        assert float(latest["w"][0]) == 3.0
        best, best_meta = mgr.restore_best(template)
        assert float(best["w"][0]) == 2.0
        assert best_meta["metric"] == 0.2

    def test_fresh_instance_resumes_rotation(self, tmp_path):
        from distributed_pytorch_tpu.checkpoint import CheckpointManager

        d = str(tmp_path / "c")
        CheckpointManager(d, keep=2).save(
            self._state(1), step=1, metric=0.1
        )
        # A NEW process (fresh manager) continues pruning correctly from
        # what is on disk.
        mgr2 = CheckpointManager(d, keep=2)
        for step, metric in [(2, 0.5), (3, 0.6), (4, 0.7)]:
            mgr2.save(self._state(step), step=step, metric=metric)
        steps = sorted(
            int(p.name[5:-4]) for p in (tmp_path / "c").glob("ckpt_*.npz")
        )
        assert steps == [1, 3, 4]  # 1 = best, 3/4 = newest two

    def test_no_metric_keeps_recency_only(self, tmp_path):
        from distributed_pytorch_tpu.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "c"), keep=2)
        for step in (1, 2, 3):
            mgr.save(self._state(step), step=step)
        steps = sorted(
            int(p.name[5:-4]) for p in (tmp_path / "c").glob("ckpt_*.npz")
        )
        assert steps == [2, 3]
        with pytest.raises(FileNotFoundError):
            mgr.restore_best({"w": jnp.zeros((4,))})

    def test_mode_max_protects_highest(self, tmp_path):
        from distributed_pytorch_tpu.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "c"), keep=1, mode="max")
        for step, metric in [(1, 0.9), (2, 0.1), (3, 0.2)]:
            mgr.save(self._state(step), step=step, metric=metric)
        steps = sorted(
            int(p.name[5:-4]) for p in (tmp_path / "c").glob("ckpt_*.npz")
        )
        assert steps == [1, 3]  # 1 = best accuracy, 3 = newest

    def test_rejects_bad_config(self, tmp_path):
        from distributed_pytorch_tpu.checkpoint import CheckpointManager

        with pytest.raises(ValueError, match="keep"):
            CheckpointManager(str(tmp_path), keep=0)
        with pytest.raises(ValueError, match="mode"):
            CheckpointManager(str(tmp_path), mode="median")


class TestCheckpointManagerEdgeCases:
    """Review-hardened behaviors: rollback resume, NaN metrics, unreadable
    files."""

    def _state(self, seed):
        return {"w": jnp.full((4,), float(seed))}

    def test_rollback_resume_keeps_fresh_low_step_saves(self, tmp_path):
        import time as _time

        from distributed_pytorch_tpu.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "c"), keep=2)
        for step, metric in [(1, 0.1), (3, 0.5), (4, 0.6)]:
            mgr.save(self._state(step), step=step, metric=metric)
            _time.sleep(0.02)
        # Roll back to best (step 1) and resume: the resumed run's step-2
        # save must SURVIVE its own prune and become the latest.
        mgr.save(self._state(2), step=2, metric=0.4)
        assert os.path.exists(tmp_path / "c" / "ckpt_0000000002.npz")
        assert mgr.latest_path().endswith("ckpt_0000000002.npz")

    def test_nan_metric_never_becomes_best(self, tmp_path):
        from distributed_pytorch_tpu.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "c"), keep=5)
        mgr.save(self._state(1), step=1, metric=float("nan"))
        mgr.save(self._state(2), step=2, metric=0.7)
        best, meta = mgr.restore_best({"w": jnp.zeros((4,))})
        assert meta["metric"] == 0.7
        assert float(best["w"][0]) == 2.0

    def test_unreadable_file_is_protected_not_pruned(self, tmp_path):
        import time as _time

        from distributed_pytorch_tpu.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "c"), keep=1)
        mgr.save(self._state(1), step=1, metric=0.1)
        # Corrupt step 1 (simulates a transient/partial read): it can no
        # longer prove it's the best, but pruning must NOT delete what it
        # cannot read.
        (tmp_path / "c" / "ckpt_0000000001.npz").write_bytes(b"garbage")
        _time.sleep(0.02)
        mgr.save(self._state(2), step=2, metric=0.5)
        names = sorted(p.name for p in (tmp_path / "c").glob("ckpt_*.npz"))
        assert names == [
            "ckpt_0000000001.npz",
            "ckpt_0000000002.npz",
        ]


def test_manager_permanently_corrupt_file_eventually_pruned(tmp_path):
    """A transient glitch protects a file; a PERMANENTLY corrupt one stops
    being protected after a few failed reads (no unbounded accumulation)."""
    import time as _time

    from distributed_pytorch_tpu.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "c"), keep=1)
    mgr.save({"w": jnp.zeros((2,))}, step=1, metric=0.1)
    (tmp_path / "c" / "ckpt_0000000001.npz").write_bytes(b"garbage")
    for step in (2, 3, 4, 5):
        _time.sleep(0.01)
        mgr.save({"w": jnp.zeros((2,))}, step=step, metric=0.5)
    names = sorted(p.name for p in (tmp_path / "c").glob("ckpt_*.npz"))
    assert "ckpt_0000000001.npz" not in names  # pruned after repeated fails
    assert names[-1] == "ckpt_0000000005.npz"


def test_trainer_rejects_snapshot_plus_rotation(tmp_path):
    import optax

    from distributed_pytorch_tpu.models.toy import ToyRegressor
    from distributed_pytorch_tpu.training.trainer import Trainer
    from distributed_pytorch_tpu.utils.data import (
        MaterializedDataset,
        ShardedLoader,
    )

    with pytest.raises(ValueError, match="keep_checkpoints"):
        Trainer(
            ToyRegressor(),
            ShardedLoader(MaterializedDataset(32), 16),
            optax.sgd(1e-2),
            save_every=1,
            snapshot_path=str(tmp_path / "s.npz"),
            checkpoint_path=str(tmp_path / "c"),
            keep_checkpoints=2,
        )
