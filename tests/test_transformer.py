"""TransformerLM + ViT: shapes, causality, sequence-parallel parity, training."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_pytorch_tpu.models import TransformerLM, ViT
from distributed_pytorch_tpu.parallel.mesh import make_mesh
from distributed_pytorch_tpu.parallel.sharding import replicated_sharding
from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss
from distributed_pytorch_tpu.training.train_step import (
    create_train_state,
    make_train_step,
)

TINY = dict(vocab_size=128, d_model=32, n_layers=2, n_heads=4, d_ff=64)


def _tokens(b=4, t=32, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, TINY["vocab_size"], (b, t)), jnp.int32)


def test_lm_forward_shape():
    model = TransformerLM(**TINY)
    tokens = _tokens()
    variables = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(variables, tokens)
    assert logits.shape == (4, 32, TINY["vocab_size"])


def test_lm_is_causal():
    model = TransformerLM(**TINY)
    tokens = _tokens(b=1)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    logits1 = model.apply(variables, tokens)
    perturbed = tokens.at[0, -1].set((tokens[0, -1] + 1) % TINY["vocab_size"])
    logits2 = model.apply(variables, perturbed)
    np.testing.assert_allclose(
        np.asarray(logits1[0, :-1]), np.asarray(logits2[0, :-1]), rtol=1e-5, atol=1e-5
    )


@pytest.mark.slow
def test_lm_sequence_parallel_matches_dense():
    """The long-context contract: a TransformerLM running ring attention over a
    sequence-sharded mesh produces the same logits as the dense model."""
    mesh = make_mesh({"data": 2, "sequence": 4})
    dense = TransformerLM(**TINY)
    ring = TransformerLM(**TINY, mesh=mesh, sequence_axis="sequence")
    tokens = _tokens()
    variables = dense.init(jax.random.PRNGKey(0), tokens)
    out_dense = dense.apply(variables, tokens)
    out_ring = ring.apply(variables, tokens)  # same params, SP execution
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_dense), rtol=2e-4, atol=2e-4
    )


def test_lm_ulysses_sequence_parallel_matches_dense():
    """The all-to-all SP alternative: same params, sequence_mode="ulysses"
    (seq->head redistribution, local full-T attention) must reproduce the
    dense logits exactly like the ring path does. n_heads=4 = sp size, the
    tightest legal head split."""
    mesh = make_mesh({"data": 2, "sequence": 4})
    dense = TransformerLM(**TINY)
    uly = TransformerLM(
        **TINY, mesh=mesh, sequence_axis="sequence", sequence_mode="ulysses"
    )
    tokens = _tokens()
    variables = dense.init(jax.random.PRNGKey(0), tokens)
    out_dense = dense.apply(variables, tokens)
    out_uly = uly.apply(variables, tokens)
    np.testing.assert_allclose(
        np.asarray(out_uly), np.asarray(out_dense), rtol=2e-4, atol=2e-4
    )


def test_lm_rejects_unknown_sequence_mode():
    mesh = make_mesh({"data": 2, "sequence": 4})
    lm = TransformerLM(
        **TINY, mesh=mesh, sequence_axis="sequence", sequence_mode="spiral"
    )
    tokens = _tokens()
    with pytest.raises(ValueError, match="sequence_mode"):
        lm.init(jax.random.PRNGKey(0), tokens)
    # A typo must fail even where no sequence axis is in play (single-chip
    # dev configs) — not surface later when the job first meets an sp mesh.
    plain = TransformerLM(**TINY, sequence_mode="spiral")
    with pytest.raises(ValueError, match="sequence_mode"):
        plain.init(jax.random.PRNGKey(0), tokens)


@pytest.mark.slow
def test_lm_trains_and_loss_decreases():
    model = TransformerLM(**TINY)
    opt = optax.adam(1e-3)
    tokens = _tokens(b=8, t=16)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    state = create_train_state(model, opt, inputs)
    step = make_train_step(model.apply, opt, softmax_cross_entropy_loss)
    first = last = None
    for _ in range(30):
        state, loss = step(state, (inputs, targets))
        if first is None:
            first = float(loss)
        last = float(loss)
    assert last < first * 0.8


@pytest.mark.slow
def test_lm_remat_matches_no_remat():
    tokens = _tokens()
    plain = TransformerLM(**TINY)
    remat = TransformerLM(**TINY, remat=True)
    variables = plain.init(jax.random.PRNGKey(0), tokens)

    def loss(m, v):
        return jnp.mean(m.apply(v, tokens) ** 2)

    g1 = jax.grad(lambda v: loss(plain, v))(variables)
    g2 = jax.grad(lambda v: loss(remat, v))(variables)
    for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_vit_forward_and_train_step():
    model = ViT(
        patch_size=8, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        num_classes=10, image_size=32,
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 32, 32, 3)), jnp.float32)
    y = jnp.asarray([1, 2], jnp.int32)
    opt = optax.adam(1e-3)
    state = create_train_state(model, opt, x)
    step = make_train_step(model.apply, opt, softmax_cross_entropy_loss)
    state, loss = step(state, (x, y))
    assert np.isfinite(float(loss))


def test_vit_l32_param_count():
    """~306M params, the number the reference's comment quotes for vit_l_32
    (multigpu_profile.py:24). Counted via eval_shape (no memory needed)."""
    from distributed_pytorch_tpu.models import ViT_L32

    model = ViT_L32()
    shapes = jax.eval_shape(
        model.init, jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3))
    )
    n = sum(np.prod(s.shape) for s in jax.tree_util.tree_leaves(shapes))
    assert 290e6 < n < 320e6, n


@pytest.mark.slow
def test_lm_dp_training_matches_serial():
    """DP mesh training parity for the transformer (same contract as the toy)."""
    mesh = make_mesh({"data": 8})
    tokens = _tokens(b=16, t=16, seed=3)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    opt = optax.sgd(1e-2)
    model = TransformerLM(**TINY)

    s1 = create_train_state(model, opt, inputs, rng_seed=1)
    s2 = jax.device_put(
        create_train_state(model, opt, inputs, rng_seed=1), replicated_sharding(mesh)
    )
    serial = make_train_step(model.apply, opt, softmax_cross_entropy_loss)
    dp = make_train_step(model.apply, opt, softmax_cross_entropy_loss, mesh=mesh)
    from distributed_pytorch_tpu.parallel.sharding import put_global_batch

    for _ in range(3):
        s1, l1 = serial(s1, (inputs, targets))
        s2, l2 = dp(s2, put_global_batch(mesh, (np.asarray(inputs), np.asarray(targets))))
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)


class TestRematPolicy:
    """remat / remat_policy variants must be numerically identical — they
    trade memory for recompute, never math (the 'mlp' policy keeps attention
    kernels un-recomputed; measured +18% step time for 'full' at T=8192 on
    v5e, BASELINE.md round 3)."""

    @pytest.mark.slow
    def test_policies_match_no_remat(self):
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
        targets = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)

        losses = {}
        for name, kw in {
            "none": dict(remat=False),
            "full": dict(remat=True, remat_policy="full"),
            "mlp": dict(remat=True, remat_policy="mlp"),
        }.items():
            model = TransformerLM(
                vocab_size=64, d_model=16, n_layers=2, n_heads=2, d_ff=32, **kw
            )
            opt = optax.sgd(1e-2)
            state = create_train_state(model, opt, tokens)
            step = make_train_step(model.apply, opt, softmax_cross_entropy_loss)
            for _ in range(3):
                state, loss = step(state, (tokens, targets))
            losses[name] = float(loss)
        np.testing.assert_allclose(losses["none"], losses["full"], rtol=1e-6)
        np.testing.assert_allclose(losses["none"], losses["mlp"], rtol=1e-6)

    def test_unknown_policy_raises(self):
        import pytest

        model = TransformerLM(
            vocab_size=64, d_model=16, n_layers=1, n_heads=2, d_ff=32,
            remat=True, remat_policy="everything",
        )
        with pytest.raises(ValueError, match="remat_policy"):
            model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))


class TestGroupedQueryAttention:
    """GQA (n_kv_heads < n_heads): K/V project to fewer heads, the decode
    cache stores only those, and query groups share them — the standard
    KV-cache cut, multiplicative with the int8 cache."""

    GQA = dict(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        n_kv_heads=2,
    )

    def _tokens(self, b=2, t=16, seed=0):
        rng = np.random.default_rng(seed)
        return jnp.asarray(rng.integers(0, 64, (b, t)), jnp.int32)

    def test_param_and_cache_shapes_shrink(self):
        model = TransformerLM(**self.GQA)
        tokens = self._tokens()
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        attn = params["block_0"]["attention"]
        assert attn["query"]["kernel"].shape == (32, 4, 8)
        assert attn["key"]["kernel"].shape == (32, 2, 8)
        assert attn["value"]["kernel"].shape == (32, 2, 8)
        cache = model.clone(decode=True).init(
            jax.random.PRNGKey(0), tokens
        )["cache"]
        # The decode cache holds n_kv_heads — HALF the MHA bytes here.
        assert cache["block_0"]["attention"]["cached_key"].shape == (
            2, 16, 2, 8,
        )

    def test_decode_matches_full_forward(self):
        """The incremental GQA decode path (small cache + post-read head
        broadcast) must reproduce the full-context forward logits."""
        model = TransformerLM(**self.GQA)
        tokens = self._tokens()
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        full = model.apply({"params": params}, tokens)
        dec = model.clone(decode=True)
        cache = dec.init(jax.random.PRNGKey(0), tokens)["cache"]
        steps = []
        for t in range(tokens.shape[1]):
            logits, updated = dec.apply(
                {"params": params, "cache": cache},
                tokens[:, t : t + 1],
                mutable=["cache"],
            )
            cache = updated["cache"]
            steps.append(logits[:, 0])
        np.testing.assert_allclose(
            np.asarray(jnp.stack(steps, axis=1)), np.asarray(full),
            rtol=1e-4, atol=1e-4,
        )

    def test_nkv_equal_heads_is_exactly_mha(self):
        mha = TransformerLM(**{**self.GQA, "n_kv_heads": 0})
        gqa_full = TransformerLM(**{**self.GQA, "n_kv_heads": 4})
        tokens = self._tokens()
        params = mha.init(jax.random.PRNGKey(0), tokens)["params"]
        out_a = mha.apply({"params": params}, tokens)
        out_b = gqa_full.apply({"params": params}, tokens)  # same tree
        np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_b))

    def test_rejects_indivisible_heads(self):
        model = TransformerLM(**{**self.GQA, "n_kv_heads": 3})
        with pytest.raises(ValueError, match="n_kv_heads"):
            model.init(jax.random.PRNGKey(0), self._tokens())

    def test_int8_cache_composes(self):
        from distributed_pytorch_tpu.generation import generate

        model = TransformerLM(**self.GQA)
        tokens = self._tokens()
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        dec = model.clone(decode=True, quantized_cache=True)
        cache = dec.init(jax.random.PRNGKey(0), tokens)["cache"]
        entry = cache["block_0"]["attention"]
        assert entry["cached_key"].dtype == jnp.int8
        assert entry["cached_key"].shape == (2, 16, 2, 8)
        assert entry["key_scale"].shape == (2, 16, 2)
        out = generate(
            model, params, tokens[:, :8], 5, quantized_cache=True
        )
        assert out.shape == (2, 13)

    def test_sequence_parallel_modes_match_dense(self):
        """GQA broadcast happens before the SP cores, so ring and ulysses
        must both reproduce the dense GQA logits. n_heads=4 = sp size after
        broadcast; kv stays at 2."""
        mesh = make_mesh({"data": 2, "sequence": 4})
        dense = TransformerLM(**self.GQA)
        tokens = self._tokens(t=32)
        variables = dense.init(jax.random.PRNGKey(0), tokens)
        ref = dense.apply(variables, tokens)
        for mode in ("ring", "ulysses"):
            sp = TransformerLM(
                **self.GQA, mesh=mesh, sequence_axis="sequence",
                sequence_mode=mode,
            )
            out = sp.apply(variables, tokens)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4,
                err_msg=mode,
            )


class TestSlidingWindowModel:
    """attention_window at the model level: locality of the receptive field,
    windowed decode parity, and the explicit not-with-SP gate."""

    WIN = dict(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        attention_window=6,
    )

    def _tokens(self, b=2, t=24, seed=0):
        rng = np.random.default_rng(seed)
        return jnp.asarray(rng.integers(0, 64, (b, t)), jnp.int32)

    def test_receptive_field_is_local(self):
        """Perturbing token 0 must not move logits beyond the stacked
        window reach (2 layers x window 6 -> positions >= 12 see nothing
        of it), while early positions DO change."""
        model = TransformerLM(**self.WIN)
        tokens = self._tokens()
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        base = model.apply({"params": params}, tokens)
        perturbed = tokens.at[0, 0].set((tokens[0, 0] + 7) % 64)
        out = model.apply({"params": params}, perturbed)
        np.testing.assert_allclose(
            np.asarray(base[0, 12:]), np.asarray(out[0, 12:]),
            rtol=1e-5, atol=1e-5,
        )
        assert float(jnp.abs(base[0, :6] - out[0, :6]).max()) > 1e-6

    def test_windowed_decode_matches_full_forward(self):
        model = TransformerLM(**self.WIN)
        tokens = self._tokens()
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        full = model.apply({"params": params}, tokens)
        dec = model.clone(decode=True)
        cache = dec.init(jax.random.PRNGKey(0), tokens)["cache"]
        steps = []
        for t in range(tokens.shape[1]):
            logits, updated = dec.apply(
                {"params": params, "cache": cache},
                tokens[:, t : t + 1],
                mutable=["cache"],
            )
            cache = updated["cache"]
            steps.append(logits[:, 0])
        np.testing.assert_allclose(
            np.asarray(jnp.stack(steps, axis=1)), np.asarray(full),
            rtol=1e-4, atol=1e-4,
        )

    def test_window_composes_with_gqa_decode(self):
        model = TransformerLM(**{**self.WIN, "n_kv_heads": 2})
        tokens = self._tokens(t=16)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        full = model.apply({"params": params}, tokens)
        dec = model.clone(decode=True)
        cache = dec.init(jax.random.PRNGKey(0), tokens)["cache"]
        steps = []
        for t in range(tokens.shape[1]):
            logits, updated = dec.apply(
                {"params": params, "cache": cache},
                tokens[:, t : t + 1],
                mutable=["cache"],
            )
            cache = updated["cache"]
            steps.append(logits[:, 0])
        np.testing.assert_allclose(
            np.asarray(jnp.stack(steps, axis=1)), np.asarray(full),
            rtol=1e-4, atol=1e-4,
        )

    def test_window_composes_with_sequence_parallelism(self):
        """Ring and ulysses must reproduce the dense windowed logits on a
        dp x sp mesh (closes VERDICT r04 item 3 — this combination used to
        raise)."""
        mesh = make_mesh({"data": 2, "sequence": 4})
        dense = TransformerLM(**self.WIN)
        tokens = self._tokens(t=32)
        variables = dense.init(jax.random.PRNGKey(0), tokens)
        ref = dense.apply(variables, tokens)
        for mode in ("ring", "ulysses"):
            sp = TransformerLM(
                **self.WIN, mesh=mesh, sequence_axis="sequence",
                sequence_mode=mode,
            )
            out = sp.apply(variables, tokens)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4,
                err_msg=mode,
            )


class TestRopeScaling:
    """Context-extension knobs: linear position interpolation (rope_scale)
    and frequency base (rope_theta)."""

    def test_scale_is_position_division(self):
        from distributed_pytorch_tpu.models.transformer import apply_rope

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((1, 8, 2, 16)), jnp.float32)
        scaled = apply_rope(x, scale=4.0)
        manual = apply_rope(
            x, positions=jnp.arange(8, dtype=jnp.float32) / 4.0
        )
        np.testing.assert_allclose(
            np.asarray(scaled), np.asarray(manual), rtol=1e-6
        )
        # scale=1 is the identity parameterization.
        np.testing.assert_array_equal(
            np.asarray(apply_rope(x)), np.asarray(apply_rope(x, scale=1.0))
        )

    def test_scaled_decode_matches_full_forward(self):
        """The decode path must rotate by the SAME scaled positions as the
        full forward — otherwise cache decode drifts from training."""
        model = TransformerLM(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
            rope_scale=2.0, rope_theta=50000.0,
        )
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 64, (2, 12)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        full = model.apply({"params": params}, tokens)
        dec = model.clone(decode=True)
        cache = dec.init(jax.random.PRNGKey(0), tokens)["cache"]
        steps = []
        for t in range(tokens.shape[1]):
            logits, updated = dec.apply(
                {"params": params, "cache": cache},
                tokens[:, t : t + 1],
                mutable=["cache"],
            )
            cache = updated["cache"]
            steps.append(logits[:, 0])
        np.testing.assert_allclose(
            np.asarray(jnp.stack(steps, axis=1)), np.asarray(full),
            rtol=1e-4, atol=1e-4,
        )

    def test_scaling_changes_long_range_attention(self):
        """The knobs must actually do something: scaled and unscaled models
        with identical params produce different logits."""
        kw = dict(
            vocab_size=64, d_model=32, n_layers=1, n_heads=4, d_ff=64
        )
        plain = TransformerLM(**kw)
        scaled = TransformerLM(**kw, rope_scale=8.0)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 64, (1, 32)), jnp.int32)
        params = plain.init(jax.random.PRNGKey(0), tokens)["params"]
        a = plain.apply({"params": params}, tokens)
        b = scaled.apply({"params": params}, tokens)
        assert float(jnp.abs(a - b).max()) > 1e-4


class TestTiedEmbeddings:
    """tie_embeddings=True: the LM head is the transposed token embedding —
    vocab*d_model + vocab fewer params, gradients reach the embedding from
    both ends, and every head path (dense logits, fused CE, decode) uses
    the same tied matrix."""

    KW = dict(vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)

    def _tokens(self, b=4, t=17, seed=0):
        rng = np.random.default_rng(seed)
        return jnp.asarray(rng.integers(0, 64, (b, t)), jnp.int32)

    def test_param_tree_drops_lm_head(self):
        tokens = self._tokens()
        tied = TransformerLM(**self.KW, tie_embeddings=True)
        untied = TransformerLM(**self.KW)
        pt = tied.init(jax.random.PRNGKey(0), tokens)["params"]
        pu = untied.init(jax.random.PRNGKey(0), tokens)["params"]
        assert "lm_head" not in pt and "lm_head" in pu
        nt = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(pt))
        nu = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(pu))
        assert nu - nt == 64 * 32 + 64  # kernel + bias gone

    def test_logits_use_embedding_transpose(self):
        tokens = self._tokens(b=1, t=8)
        tied = TransformerLM(**self.KW, tie_embeddings=True)
        variables = tied.init(jax.random.PRNGKey(0), tokens)
        logits = tied.apply(variables, tokens)
        # Reconstruct by hand: trunk output @ embedding.T.
        emb = variables["params"]["embed"]["embedding"]
        # Perturb the embedding with NOISE (a constant shift would cancel
        # through the final LayerNorm's zero-mean output): logits must
        # move, because the head IS the embedding.
        noise = jax.random.normal(jax.random.PRNGKey(7), emb.shape) * 0.01
        v2 = jax.tree_util.tree_map(lambda x: x, variables)
        v2["params"]["embed"]["embedding"] = emb + noise
        logits2 = tied.apply(v2, tokens)
        assert float(jnp.abs(logits - logits2).max()) > 1e-3

    def test_trains_and_fused_head_matches_dense(self):
        import optax

        tokens = self._tokens()
        tied = TransformerLM(**self.KW, tie_embeddings=True)
        params = tied.init(jax.random.PRNGKey(0), tokens[:, :-1])["params"]
        tied_fused = TransformerLM(
            **self.KW, tie_embeddings=True, fused_head_chunk=32
        )
        dense_logits = tied.apply({"params": params}, tokens[:, :-1])
        fused_loss = tied_fused.apply(
            {"params": params}, tokens[:, :-1], tokens[:, 1:]
        )
        ce = optax.softmax_cross_entropy_with_integer_labels(
            dense_logits, tokens[:, 1:]
        ).mean()
        np.testing.assert_allclose(
            float(fused_loss), float(ce), rtol=1e-5
        )

    def test_tied_decode_matches_full_forward(self):
        model = TransformerLM(**self.KW, tie_embeddings=True)
        tokens = self._tokens(b=2, t=12)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        full = model.apply({"params": params}, tokens)
        dec = model.clone(decode=True)
        cache = dec.init(jax.random.PRNGKey(0), tokens)["cache"]
        steps = []
        for t in range(tokens.shape[1]):
            logits, updated = dec.apply(
                {"params": params, "cache": cache},
                tokens[:, t : t + 1],
                mutable=["cache"],
            )
            cache = updated["cache"]
            steps.append(logits[:, 0])
        np.testing.assert_allclose(
            np.asarray(jnp.stack(steps, axis=1)), np.asarray(full),
            rtol=1e-4, atol=1e-4,
        )
