"""Production-observability tests: the crash-dump FlightRecorder (ring
buffer, postmortem dumps, Perfetto replay), the SLO burn-rate monitor
(latency + rate objectives, rising-edge alerting, registry/tracer/flight
fan-out), goodput/MFU accounting (per-step waste attribution, the shared
FLOPs model), the registry's HELP/escape/read accessors — and the engine
integration acceptance criteria: with recorder + SLO monitor + goodput
all enabled, greedy outputs are bitwise-identical to the all-off engine;
chaos faults and unhandled run() exceptions leave a postmortem dump; a
snapshot/restore cycle attributes nonzero waste to restore re-prefill.
"""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu import chaos
from distributed_pytorch_tpu.metrics import ReservoirGroup, ReservoirHistogram
from distributed_pytorch_tpu.obs import (
    FlightRecorder,
    GoodputTracker,
    MetricsRegistry,
    NULL_FLIGHT_RECORDER,
    NullFlightRecorder,
    SLObjective,
    SLOMonitor,
    Tracer,
    causal_attention_flops,
    default_serving_objectives,
    peak_flops_per_chip,
    replay_to_tracer,
    transformer_decode_flops_per_token,
    transformer_train_flops,
)
from distributed_pytorch_tpu.obs.goodput import DEFAULT_PEAK, WASTE_KINDS
from distributed_pytorch_tpu.serving import (
    InferenceEngine,
    SamplingParams,
    restore_engine,
    snapshot_engine,
)


class FakeClock:
    """Deterministic clock: advances a fixed tick per call."""

    def __init__(self, tick: float = 0.001):
        self.t = 0.0
        self.tick = tick

    def __call__(self) -> float:
        self.t += self.tick
        return self.t


# --------------------------------------------------------- flight recorder


class TestFlightRecorder:
    def test_ring_drops_oldest_and_counts(self):
        fr = FlightRecorder(capacity=3, clock=FakeClock())
        for i in range(5):
            fr.record("step", step=i)
        assert fr.recorded == 5 and fr.dropped == 2
        events = fr.events()
        assert [e["step"] for e in events] == [2, 3, 4]  # oldest fell off
        assert all(e["kind"] == "step" for e in events)
        # timestamps are seconds since construction, strictly increasing
        ts = [e["t"] for e in events]
        assert ts == sorted(ts) and ts[0] >= 0.0

    def test_capacity_validated(self):
        with pytest.raises(ValueError):
            FlightRecorder(capacity=0)

    def test_dump_document_shape_without_path(self):
        fr = FlightRecorder(capacity=8, clock=FakeClock())
        fr.record("admit", req_id=1)
        doc = fr.dump("manual", extra={"registry": {"counters": {}}})
        assert doc["version"] == 1
        assert doc["reason"] == "manual"
        assert doc["recorded"] == 1 and doc["dropped"] == 0
        assert doc["capacity"] == 8
        assert doc["events"][0]["kind"] == "admit"
        assert doc["extra"]["registry"] == {"counters": {}}
        assert fr.dumps == 1

    def test_dump_writes_atomically(self, tmp_path):
        target = tmp_path / "sub" / "postmortem.json"
        fr = FlightRecorder(capacity=8, path=str(target), clock=FakeClock())
        fr.record("step", step=0, dur_s=0.01)
        fr.dump("chaos:kill")
        with open(target) as f:
            doc = json.load(f)
        assert doc["reason"] == "chaos:kill"
        assert doc["events"][0]["step"] == 0
        # no .tmp leftovers from the atomic replace
        assert all(
            ".tmp." not in name for name in os.listdir(target.parent)
        )
        # a second dump overwrites in place (latest reason wins)
        fr.dump("close")
        assert json.load(open(target))["reason"] == "close"

    def test_null_recorder_is_inert(self):
        assert NULL_FLIGHT_RECORDER.enabled is False
        assert isinstance(NULL_FLIGHT_RECORDER, NullFlightRecorder)
        NULL_FLIGHT_RECORDER.record("anything", x=1)
        assert NULL_FLIGHT_RECORDER.dump("reason") is None
        assert not hasattr(NULL_FLIGHT_RECORDER, "events")


class TestReplayToTracer:
    def _dump(self):
        fr = FlightRecorder(capacity=16, clock=FakeClock(0.01))
        fr.record("admit", req_id=1, slot=0)
        fr.record("step", step=0, dur_s=0.005, emitted_tokens=2)
        fr.record("chaos_fault", fault_kind="kill_mid_verify", step=1)
        return fr.dump("chaos:kill_mid_verify")

    def test_replay_produces_valid_chrome_trace(self):
        tracer = replay_to_tracer(self._dump())
        doc = json.loads(json.dumps(tracer.to_perfetto()))
        events = doc["traceEvents"]
        steps = [e for e in events if e.get("ph") == "X"]
        assert len(steps) == 1
        assert steps[0]["name"] == "step" and steps[0]["dur"] > 0
        assert steps[0]["args"]["emitted_tokens"] == 2
        instants = {
            e["name"] for e in events if e.get("ph") == "i"
        }
        assert {"admit", "chaos_fault"} <= instants
        # lane metadata came along from to_perfetto()
        assert any(e.get("ph") == "M" for e in events)

    def test_replay_accepts_json_text_and_path(self, tmp_path):
        doc = self._dump()
        by_text = replay_to_tracer(json.dumps(doc))
        path = tmp_path / "dump.json"
        path.write_text(json.dumps(doc))
        by_path = replay_to_tracer(str(path))
        by_dict = replay_to_tracer(doc)
        assert (
            len(by_text.events) == len(by_path.events) == len(by_dict.events)
        )

    def test_replay_into_existing_tracer(self):
        tr = Tracer(clock=FakeClock())
        out = replay_to_tracer(self._dump(), tracer=tr)
        assert out is tr and tr.events

    def test_replay_rejects_non_dump(self):
        with pytest.raises(ValueError):
            replay_to_tracer({"not": "a dump"})


# ------------------------------------------------------- registry accessors


class TestRegistryAccessors:
    def test_read_counter_gauge_and_quantile(self):
        reg = MetricsRegistry(namespace="srv")
        reg.counter("reqs_total").inc(4)
        reg.gauge("depth", 2.5)
        h = ReservoirHistogram(64, seed=0)
        for v in (1.0, 2.0, 3.0):
            h.record(v)
        reg.reservoir("lat_seconds", h)
        # both name forms resolve: registered and namespace-qualified
        assert reg.read_counter("reqs_total") == 4
        assert reg.read_counter("srv_reqs_total") == 4
        assert reg.read_gauge("depth") == 2.5
        assert reg.read_quantile("lat_seconds", 0.5) == 2.0

    def test_read_quantile_labeled(self):
        reg = MetricsRegistry(namespace="srv")
        grp = ReservoirGroup(("hit", "miss"), 64, seed=1)
        grp.record("hit", 0.25)
        reg.reservoir("ttft_by_source", grp, label="source")
        assert reg.read_quantile(
            "ttft_by_source", 0.5, label_value="hit"
        ) == 0.25
        # empty series and unknown labels read as NaN, not KeyError
        assert math.isnan(
            reg.read_quantile("ttft_by_source", 0.5, label_value="miss")
        )
        assert math.isnan(
            reg.read_quantile("ttft_by_source", 0.5, label_value="nope")
        )
        with pytest.raises(ValueError):
            reg.read_quantile("ttft_by_source", 0.5)  # label required

    def test_prometheus_help_lines_precede_type(self):
        reg = MetricsRegistry(namespace="srv")
        reg.counter("reqs_total", help="Total requests admitted")
        reg.gauge("depth", 1.0)
        text = reg.prometheus_text()
        assert "# HELP srv_reqs_total Total requests admitted" in text
        assert text.index("# HELP srv_reqs_total") < text.index(
            "# TYPE srv_reqs_total counter"
        )
        # metrics registered without help fall back to their own name
        assert "# HELP srv_depth srv_depth" in text

    def test_prometheus_escapes_help_and_labels(self):
        reg = MetricsRegistry(namespace="srv")
        reg.counter("weird_total", help="line1\nline2 back\\slash")
        grp = ReservoirGroup(('he"llo\n', ), 8)
        grp.record('he"llo\n', 1.0)
        reg.reservoir("lat by source!", grp, label="the source")
        text = reg.prometheus_text()
        assert "# HELP srv_weird_total line1\\nline2 back\\\\slash" in text
        # label-unsafe metric name sanitized, label value escaped
        assert "srv_lat_by_source_" in text
        assert 'the_source="he\\"llo\\n"' in text
        assert "\nline2" not in text  # no raw newline mid-HELP


# ------------------------------------------------------------- SLO monitor


class TestSLObjective:
    def test_exactly_one_form_required(self):
        with pytest.raises(ValueError):
            SLObjective(name="both", metric="m", threshold_s=1.0,
                        bad_counter="b", total_counter="t")
        with pytest.raises(ValueError):
            SLObjective(name="neither")
        with pytest.raises(ValueError):
            SLObjective(name="no_thresh", metric="m")
        with pytest.raises(ValueError):
            SLObjective(name="no_total", bad_counter="b")
        with pytest.raises(ValueError):
            SLObjective(name="bad_budget", metric="m", threshold_s=1.0,
                        budget=0.0)
        with pytest.raises(ValueError):
            SLObjective(name="windows", metric="m", threshold_s=1.0,
                        fast_window_s=10.0, slow_window_s=5.0)
        assert SLObjective(
            name="ok", metric="m", threshold_s=1.0
        ).kind == "latency"
        assert SLObjective(
            name="ok2", bad_counter="b", total_counter="t"
        ).kind == "rate"

    def test_default_serving_objectives_shape(self):
        objs = default_serving_objectives()
        assert [o.name for o in objs] == [
            "ttft_p95", "tpot_p50", "expired_rate"
        ]
        assert objs[0].kind == "latency" and objs[2].kind == "rate"


class TestSLOMonitor:
    def _latency_setup(self, threshold_s, **obj_kw):
        reg = MetricsRegistry()
        hist = ReservoirHistogram(64, seed=0)
        reg.reservoir("lat_seconds", hist)
        obj = SLObjective(
            name="lat_p50", metric="lat_seconds", quantile=0.5,
            threshold_s=threshold_s, budget=0.1,
            fast_window_s=2.0, slow_window_s=8.0, **obj_kw,
        )
        mon = SLOMonitor(reg, [obj])
        return reg, hist, mon

    def test_latency_alert_fires_once_on_rising_edge(self):
        reg, hist, mon = self._latency_setup(0.1)
        # empty reservoir: quantile is NaN -> not bad, nothing fires
        assert mon.tick(now=0.0) == []
        hist.record(0.5)  # p50 = 0.5 > 0.1: every later sample is bad
        fired = []
        for i in range(1, 10):
            fired += mon.tick(now=float(i))
        assert fired == ["lat_p50"]  # rising edge counted exactly once
        snap = reg.snapshot()
        assert snap["counters"]["slo_lat_p50_alerts_total"] == 1
        assert snap["gauges"]["slo_lat_p50_firing"] == 1.0
        assert snap["gauges"]["slo_lat_p50_burn_fast"] >= 2.0
        st = mon.state()["lat_p50"]
        assert st["firing"] and st["kind"] == "latency"
        assert st["alerts"] == 1

    def test_loose_objective_stays_quiet(self):
        reg, hist, mon = self._latency_setup(10.0)
        hist.record(0.5)  # p50 well under the threshold
        for i in range(10):
            assert mon.tick(now=float(i)) == []
        snap = reg.snapshot()
        assert snap["counters"]["slo_lat_p50_alerts_total"] == 0
        assert snap["gauges"]["slo_lat_p50_firing"] == 0.0
        assert not mon.state()["lat_p50"]["firing"]

    def test_alert_lands_in_tracer_and_flight(self):
        reg = MetricsRegistry()
        hist = ReservoirHistogram(8, seed=0)
        hist.record(1.0)
        reg.reservoir("lat_seconds", hist)
        tracer = Tracer(clock=FakeClock())
        flight = FlightRecorder(capacity=16, clock=FakeClock())
        mon = SLOMonitor(
            reg,
            [SLObjective(name="lat", metric="lat_seconds",
                         threshold_s=0.1, fast_window_s=1.0,
                         slow_window_s=4.0)],
            tracer=tracer, flight=flight,
        )
        for i in range(5):
            mon.tick(now=float(i))
        instants = [
            e for e in tracer.events if e["name"] == "slo_alert"
        ]
        assert len(instants) == 1
        assert instants[0]["args"]["objective"] == "lat"
        alerts = [e for e in flight.events() if e["kind"] == "slo_alert"]
        assert len(alerts) == 1 and alerts[0]["burn_fast"] > 0

    def test_rate_objective_fires_on_error_burst(self):
        reg = MetricsRegistry()
        bad = reg.counter("expired_total")
        total = reg.counter("accepted_total")
        mon = SLOMonitor(
            reg,
            [SLObjective(name="errs", bad_counter="expired_total",
                         total_counter="accepted_total", budget=0.1,
                         fast_window_s=2.0, slow_window_s=8.0)],
        )
        # healthy traffic: requests flow, nothing expires, never fires
        for i in range(5):
            total.inc(10)
            assert mon.tick(now=float(i)) == []
        # burst: half of everything expires -> burn >> thresholds
        fired = []
        for i in range(5, 12):
            total.inc(10)
            bad.inc(5)
            fired += mon.tick(now=float(i))
        assert fired == ["errs"]
        assert reg.snapshot()["counters"]["slo_errs_alerts_total"] == 1
        assert mon.state()["errs"]["burn_fast"] > 2.0

    def test_rate_objective_quiet_without_traffic(self):
        reg = MetricsRegistry()
        reg.counter("expired_total")
        reg.counter("accepted_total")
        mon = SLOMonitor(
            reg,
            [SLObjective(name="errs", bad_counter="expired_total",
                         total_counter="accepted_total")],
        )
        for i in range(5):  # zero denominators never divide or fire
            assert mon.tick(now=float(i)) == []

    def test_min_interval_rate_limits_ticks(self):
        reg, hist, mon = self._latency_setup(0.1)
        mon.min_interval_s = 10.0
        hist.record(1.0)
        mon.tick(now=0.0)
        assert mon.ticks == 1
        mon.tick(now=5.0)  # inside the interval: skipped
        assert mon.ticks == 1
        mon.tick(now=15.0)
        assert mon.ticks == 2

    def test_duplicate_objective_names_rejected(self):
        reg = MetricsRegistry()
        reg.reservoir("lat_seconds", ReservoirHistogram(8))
        objs = [
            SLObjective(name="x", metric="lat_seconds", threshold_s=1.0),
            SLObjective(name="x", metric="lat_seconds", threshold_s=2.0),
        ]
        with pytest.raises(ValueError):
            SLOMonitor(reg, objs)


# ---------------------------------------------------------------- goodput


class TestGoodputTracker:
    def test_fully_productive_step(self):
        t = GoodputTracker()
        t.note_step(1.0, prefill_tokens=10, budget_used=10,
                    token_budget=10, queue_depth=1)
        assert t.productive_s == pytest.approx(1.0)
        assert t.wasted_total_s() == 0.0
        assert t.fraction() == pytest.approx(1.0)

    def test_budget_idle_charged_only_under_queue_pressure(self):
        t = GoodputTracker()
        # half-used budget with a queue: half the span is idle waste
        t.note_step(1.0, prefill_tokens=5, budget_used=5,
                    token_budget=10, queue_depth=3)
        assert t.wasted["budget_idle"] == pytest.approx(0.5)
        assert t.productive_s == pytest.approx(0.5)
        # same shape with an empty queue: nothing to admit, no waste
        t2 = GoodputTracker()
        t2.note_step(1.0, prefill_tokens=5, budget_used=5,
                     token_budget=10, queue_depth=0)
        assert t2.wasted["budget_idle"] == 0.0
        assert t2.productive_s == pytest.approx(1.0)

    def test_spec_rejected_attribution(self):
        t = GoodputTracker()
        # 8 speculative positions verified, 5 kept: 3/8 of the span wasted
        t.note_step(1.0, decode_positions=8, emitted_tokens=5,
                    spec_proposed=8, budget_used=8, token_budget=8,
                    queue_depth=1)
        assert t.wasted["spec_rejected"] == pytest.approx(3 / 8)
        assert t.productive_s == pytest.approx(5 / 8)
        assert t.tokens == 5

    def test_rework_charged_before_spec(self):
        t = GoodputTracker()
        t.note_step(
            1.0, prefill_tokens=10, decode_positions=0,
            rework={"restore_reprefill": 4}, budget_used=10,
            token_budget=10, queue_depth=1,
        )
        assert t.wasted["restore_reprefill"] == pytest.approx(0.4)
        assert t.productive_s == pytest.approx(0.6)
        # rework is capped at the step's work units
        t2 = GoodputTracker()
        t2.note_step(1.0, prefill_tokens=4,
                     rework={"preempt_rework": 100})
        assert t2.wasted["preempt_rework"] == pytest.approx(1.0)
        assert t2.productive_s == 0.0

    def test_zero_work_step_is_productive(self):
        t = GoodputTracker()
        t.note_step(0.5)
        assert t.productive_s == pytest.approx(0.5)

    def test_drain_downtime_brackets(self):
        clock = FakeClock(0.5)
        t = GoodputTracker(clock=clock)
        t.note_restore()  # restore without drain (fresh process): no-op
        assert t.wasted["drain_downtime"] == 0.0
        t.note_drain()
        t.note_restore()
        assert t.wasted["drain_downtime"] == pytest.approx(0.5)

    def test_mfu_and_throughput(self):
        t = GoodputTracker(flops_per_token=100.0,
                           peak_flops_per_device=1000.0, n_devices=2)
        t.note_step(1.0, decode_positions=5, emitted_tokens=5,
                    budget_used=5, token_budget=5, queue_depth=0)
        # 5 tokens x 100 flops over 1s x 2000 peak
        assert t.mfu() == pytest.approx(0.25)
        assert t.tokens_per_sec_per_device() == pytest.approx(2.5)
        rep = t.report()
        assert set(rep) == {
            "steps", "tokens", "productive_s", "wasted_s",
            "wasted_total_s", "goodput_fraction",
            "tokens_per_sec_per_device", "mfu",
        }
        assert set(rep["wasted_s"]) == set(WASTE_KINDS)

    def test_register_into_registry(self):
        t = GoodputTracker(flops_per_token=1.0, peak_flops_per_device=1.0)
        reg = MetricsRegistry(namespace="srv")
        t.register_into(reg)
        t.note_step(1.0, prefill_tokens=2, budget_used=2,
                    token_budget=4, queue_depth=1)
        snap = reg.snapshot()
        assert snap["counters"][
            "srv_goodput_productive_seconds_total"
        ] == pytest.approx(0.5)
        assert snap["counters"][
            "srv_goodput_wasted_budget_idle_seconds_total"
        ] == pytest.approx(0.5)
        assert snap["gauges"]["srv_goodput_fraction"] == pytest.approx(0.5)
        assert "srv_goodput_mfu" in snap["gauges"]

    def test_reset_zeroes_everything(self):
        t = GoodputTracker()
        t.note_step(1.0, prefill_tokens=1)
        t.reset()
        assert t.steps == 0 and t.tokens == 0
        assert t.productive_s == 0.0 and t.wasted_total_s() == 0.0
        assert t.fraction() == 1.0


class TestFlopsModel:
    def test_causal_attention_matches_bruteforce(self):
        for seq, window in ((16, None), (16, 4), (16, 32), (7, 7)):
            per_q_brute = float(
                np.minimum(np.arange(seq) + 1, window or seq).sum()
            )
            # brute force counts keys per query; the closed form halves
            # the full square, so compare through the same public call
            got = causal_attention_flops(
                n_layers=2, n_heads=3, head_dim=5, seq_len=seq,
                batch=4, window=window,
            )
            if window:
                want = 2 * 4.0 * 4 * 3 * per_q_brute * 5
            else:
                want = 2 * 4.0 * 4 * 3 * (seq**2 / 2) * 5
            assert got == pytest.approx(want), (seq, window)

    def test_windowed_closed_form_equals_key_count(self):
        # the windowed closed form must equal sum(min(i+1, w))
        for seq, w in ((10, 3), (10, 10), (10, 15), (3, 1)):
            brute = float(np.minimum(np.arange(seq) + 1, w).sum())
            got = causal_attention_flops(
                n_layers=1, n_heads=1, head_dim=1, seq_len=seq,
                batch=1, window=w,
            )
            assert got == pytest.approx(4.0 * brute), (seq, w)

    def test_train_flops_dominated_by_param_term(self):
        flops = transformer_train_flops(
            n_params=1_000_000, embed_params=100_000, n_layers=2,
            n_heads=4, head_dim=8, seq_len=128, batch=2,
        )
        tokens = 2 * 128
        assert flops > 3.0 * 2.0 * 900_000 * tokens  # attention adds more
        # the attention term is exactly the causal helper's
        attn = causal_attention_flops(
            n_layers=2, n_heads=4, head_dim=8, seq_len=128, batch=2,
        )
        assert flops == pytest.approx(
            3.0 * (2.0 * 900_000 * tokens + attn)
        )

    def test_decode_flops_grow_with_context(self):
        kw = dict(n_params=1_000_000, embed_params=100_000,
                  n_layers=2, n_heads=4, head_dim=8)
        short = transformer_decode_flops_per_token(context_len=16, **kw)
        long = transformer_decode_flops_per_token(context_len=1024, **kw)
        assert long > short > 2.0 * 900_000

    def test_peak_flops_lookup(self):
        class Dev:
            def __init__(self, kind):
                self.device_kind = kind

        assert peak_flops_per_chip(Dev("TPU v5p")) == 459e12
        assert peak_flops_per_chip(Dev("TPU v5e")) == 197e12
        assert peak_flops_per_chip(Dev("TPU v4")) == 275e12
        assert peak_flops_per_chip(Dev("cpu")) == DEFAULT_PEAK
        assert peak_flops_per_chip(object()) == DEFAULT_PEAK


# ------------------------------------------------------ engine integration


def _tiny_engine(**kw):
    from distributed_pytorch_tpu.models.transformer import TransformerLM

    model = TransformerLM(
        vocab_size=48, d_model=16, n_layers=2, n_heads=2, d_ff=32,
        dtype=jnp.float32,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("page_size", 4)
    kw.setdefault("token_budget", 16)
    kw.setdefault("max_prefill_chunk", 8)
    return InferenceEngine(model, params, **kw)


PROMPTS = [[5, 7, 11, 2, 9, 3], [1, 4, 8], [2, 2, 3, 17, 40], [6, 1, 9, 9]]


def _run_all(eng):
    ids = [
        eng.submit(p, SamplingParams(max_new_tokens=6)) for p in PROMPTS
    ]
    eng.run()
    return [eng.poll(r).generated for r in ids]


def _arm(plan):
    os.environ[chaos.ENV_VAR] = json.dumps(plan)
    chaos._reset()


def _disarm():
    os.environ.pop(chaos.ENV_VAR, None)
    chaos._reset()


class TestEngineProductionObservability:
    def test_all_obs_on_token_parity(self):
        """Acceptance: recorder + SLO monitor + goodput + tracer all on,
        greedy outputs bitwise-identical to the all-off engine."""
        plain = _run_all(_tiny_engine())
        eng = _tiny_engine(
            tracer=Tracer(),
            flight=FlightRecorder(capacity=1024),
            slo=default_serving_objectives(),
            goodput=True,
        )
        assert _run_all(eng) == plain
        # and all three subsystems actually observed the run
        assert eng.flight.recorded > 0
        rep = eng.goodput.report()
        assert rep["steps"] > 0 and rep["tokens"] > 0
        assert rep["productive_s"] > 0.0
        assert eng.slo.ticks > 0
        snap = eng.registry.snapshot()
        assert "serving_goodput_fraction" in snap["gauges"]
        assert "serving_slo_ttft_p95_alerts_total" in snap["counters"]
        assert snap["counters"]["serving_flight_events_recorded_total"] > 0

    def test_stats_carries_goodput(self):
        eng = _tiny_engine(goodput=True)
        _run_all(eng)
        s = eng.stats()
        assert 0.0 <= s["goodput_fraction"] <= 1.0
        assert s["goodput_productive_s"] > 0.0

    def test_flight_records_engine_lifecycle(self, tmp_path):
        path = str(tmp_path / "pm.json")
        eng = _tiny_engine(flight=FlightRecorder(capacity=1024, path=path))
        _run_all(eng)
        kinds = {e["kind"] for e in eng.flight.events()}
        assert {"step", "admit", "retire"} <= kinds
        eng.close()  # close() dumps a postmortem automatically
        doc = json.load(open(path))
        assert doc["reason"] == "close"
        assert "registry" in doc["extra"]

    def test_unhandled_run_exception_dumps_postmortem(self, tmp_path):
        path = str(tmp_path / "pm.json")
        eng = _tiny_engine(
            flight=FlightRecorder(capacity=256, path=path), goodput=True
        )
        eng.submit(PROMPTS[0], SamplingParams(max_new_tokens=4))

        def boom():
            raise RuntimeError("injected step failure")

        eng._step_impl = boom
        with pytest.raises(RuntimeError, match="injected step failure"):
            eng.run()
        doc = json.load(open(path))
        assert doc["reason"] == "exception"
        exc_events = [
            e for e in doc["events"] if e["kind"] == "exception"
        ]
        assert exc_events and "injected" in exc_events[0]["error"]
        assert "goodput" in doc["extra"]

    def test_chaos_fault_dumps_before_raising(self, tmp_path):
        path = str(tmp_path / "pm.json")
        _arm({"faults": [
            {"kind": "kill_mid_verify", "at_step": 2, "mode": "raise"}
        ]})
        try:
            eng = _tiny_engine(
                flight=FlightRecorder(capacity=256, path=path)
            )
            ids = [
                eng.submit(p, SamplingParams(max_new_tokens=6))
                for p in PROMPTS
            ]
            assert ids
            with pytest.raises(chaos.InjectedFault):
                eng.run()
        finally:
            _disarm()
        doc = json.load(open(path))
        # the chaos observer dumped first (reason chaos:...), then run()'s
        # crash handler overwrote with the final exception dump — the
        # chaos_fault event survives in the ring either way.
        assert doc["reason"] == "exception"
        kinds = [e["kind"] for e in doc["events"]]
        assert "chaos_fault" in kinds
        fault = next(
            e for e in doc["events"] if e["kind"] == "chaos_fault"
        )
        assert fault["fault_kind"] == "kill_mid_verify"
        assert eng.flight.dumps == 2  # chaos dump + exception dump
        # and the dump replays into a loadable trace
        tracer = replay_to_tracer(str(path))
        assert json.loads(json.dumps(tracer.to_perfetto()))["traceEvents"]

    def test_restore_attributes_reprefill_waste(self, tmp_path):
        """A snapshot/restore cycle must charge the re-prefill of
        already-committed KV to restore_reprefill."""
        eng = _tiny_engine(max_slots=2, goodput=True)
        ids = [
            eng.submit(p, SamplingParams(max_new_tokens=8))
            for p in PROMPTS + [[9, 9, 1, 2], [4, 4, 4]]
        ]
        for _ in range(4):
            eng.step()
        snap = snapshot_engine(eng)
        assert snap.requests, "drill degenerate: nothing to restore"
        assert any(r.kv_committed > 0 for r in snap.requests), (
            "no committed KV at the snapshot"
        )

        fresh = _tiny_engine(max_slots=2, goodput=True)
        restored = restore_engine(fresh, snap)
        assert restored
        fresh.run()
        for rid in restored:
            assert fresh.poll(rid).finished
        rep = fresh.goodput.report()
        assert rep["wasted_s"]["restore_reprefill"] > 0.0
        assert rep["goodput_fraction"] < 1.0
        assert ids  # silence unused warning

    def test_preemption_attributes_rework(self):
        """A preempted-and-readmitted request re-prefills its generated
        KV; goodput charges that span to preempt_rework."""
        # 9-page pool under 4 slots x staggered waves: decode exhausts the
        # pool mid-flight and the scheduler must preempt (seeded, so the
        # preemption count is deterministic on this config).
        eng = _tiny_engine(num_pages=9, goodput=True)
        rng = np.random.default_rng(0)
        for _wave in range(4):
            for _ in range(2):
                prompt = rng.integers(
                    0, 48, int(rng.integers(3, 10))
                ).tolist()
                eng.submit(
                    prompt,
                    SamplingParams(
                        max_new_tokens=int(rng.integers(4, 9))
                    ),
                )
            for _ in range(3):
                eng.step()
        eng.run()
        assert eng.scheduler.preemptions > 0, "drill degenerate: no preempt"
        rep = eng.goodput.report()
        assert rep["wasted_s"]["preempt_rework"] > 0.0
