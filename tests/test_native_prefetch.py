"""Native C++ prefetch loader tests: identical semantics to the Python
ShardedLoader, exercised across shuffling, sharding, padding, and dtypes."""

import numpy as np
import pytest

from distributed_pytorch_tpu.utils.data import (
    MaterializedDataset,
    NativeShardedLoader,
    ShardedLoader,
)


def batches_of(loader):
    return [(xs.copy(), ys.copy()) for xs, ys in loader]


@pytest.mark.parametrize("shuffle", [False, True])
def test_matches_python_loader(shuffle):
    data = MaterializedDataset(256, seed=3)
    kw = dict(batch_size=32, shuffle=shuffle, seed=7)
    py = ShardedLoader(data, **kw)
    native = NativeShardedLoader(data, **kw, num_workers=3, prefetch_depth=2)
    for epoch in range(2):
        py.set_epoch(epoch)
        native.set_epoch(epoch)
        ref = batches_of(py)
        got = batches_of(native)
        assert len(got) == len(ref)
        for (ax, ay), (bx, by) in zip(got, ref):
            np.testing.assert_array_equal(ax, bx)
            np.testing.assert_array_equal(ay, by)


def test_sharded_and_padded():
    data = MaterializedDataset(100, seed=0)  # 100/4 shards = 25 -> ragged
    for shard in range(4):
        py = ShardedLoader(
            data, 8, num_shards=4, shard_index=shard, pad_final_batch=True
        )
        native = NativeShardedLoader(
            data, 8, num_shards=4, shard_index=shard, pad_final_batch=True
        )
        ref, got = batches_of(py), batches_of(native)
        assert len(got) == len(ref) == 4  # guards against a vacuous zip below
        for (ax, ay), (bx, by) in zip(got, ref):
            np.testing.assert_array_equal(ax, bx)
            np.testing.assert_array_equal(ay, by)


def test_ragged_tail_without_padding():
    data = MaterializedDataset(70, seed=1)
    py = ShardedLoader(data, 32)
    native = NativeShardedLoader(data, 32)
    ref, got = batches_of(py), batches_of(native)
    assert len(got) == len(ref) == 3
    assert got[-1][0].shape[0] == 6  # ragged tail delivered, not dropped
    for (ax, ay), (bx, by) in zip(got, ref):
        np.testing.assert_array_equal(ax, bx)


def test_int_targets_roundtrip():
    """Byte-level gather is dtype-agnostic — int32 class targets survive."""

    class IntDataset:
        def __init__(self):
            rng = np.random.default_rng(0)
            self.inputs = rng.standard_normal((64, 5)).astype(np.float32)
            self.targets = rng.integers(0, 10, (64, 1)).astype(np.int32)

        def __len__(self):
            return 64

        def __getitem__(self, i):
            return self.inputs[i], self.targets[i]

    data = IntDataset()
    native = NativeShardedLoader(data, 16, num_workers=2)
    got = batches_of(native)
    assert len(got) == 4
    assert got[0][1].dtype == np.int32
    np.testing.assert_array_equal(
        np.concatenate([y for _, y in got]), data.targets
    )


def test_requires_materialized_dataset():
    from distributed_pytorch_tpu.utils.data import RandomDataset

    with pytest.raises(TypeError, match="materialized"):
        NativeShardedLoader(RandomDataset(16, (4,)), 4)


def test_cross_thread_stop_then_destroy():
    """The cross-thread teardown contract: prefetch_stop from ANY thread wakes
    a blocked consumer (prefetch_next returns 0 and its loop exits), then
    prefetch_destroy — after the consumer is done — frees safely. No
    deadlock, no use-after-free."""
    import ctypes
    import threading

    from distributed_pytorch_tpu.native import prefetch_library

    lib = prefetch_library()
    data = MaterializedDataset(4096, seed=1)
    x = np.ascontiguousarray(data.inputs)
    y = np.ascontiguousarray(data.targets)
    batch, n_batches = 32, 128
    table = np.ascontiguousarray(np.arange(batch * n_batches) % len(data), dtype=np.int64)
    row_x = x.dtype.itemsize * x.shape[1]
    row_y = y.dtype.itemsize * y.shape[1]

    for trial in range(8):
        handle = lib.prefetch_create(
            x.ctypes.data, y.ctypes.data, row_x, row_y,
            table.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
            table.size, batch, 2, 2,
        )
        assert handle
        consumed = []

        def consume():
            bx = np.empty((batch, x.shape[1]), x.dtype)
            by = np.empty((batch, y.shape[1]), y.dtype)
            while lib.prefetch_next(handle, bx.ctypes.data, by.ctypes.data):
                consumed.append(1)

        t = threading.Thread(target=consume)
        t.start()
        # Stop at a random-ish point mid-stream (sometimes immediately).
        if trial % 2:
            while len(consumed) < trial:
                pass
        lib.prefetch_stop(handle)  # safe while the consumer is mid-call
        t.join(timeout=30)
        assert not t.is_alive(), "consumer thread hung after cross-thread stop"
        lib.prefetch_destroy(handle)  # consumer done: free is race-free
