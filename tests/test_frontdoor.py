"""Front-door tests: streaming parity, cancellation, backpressure,
multi-tenant fair share + burst isolation, per-request model mods
(stop sequences, logit bias, grammar masks, LoRA multiplexing), and the
drain-mid-stream resume drill.

The parity invariants are the headline: greedy tokens must be BITWISE
identical streamed vs polled, through the door vs against the bare
engine, and LoRA-multiplexed vs solo — the door and the mod plumbing may
add zero-valued operands and extra dispatch groups, but never a
different token. All on CPU (conftest pins JAX_PLATFORMS=cpu).
"""

import dataclasses
import json
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.generation import generate
from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.serving import (
    FleetRouter,
    FrontDoor,
    InferenceEngine,
    Mods,
    RequestSnapshot,
    SamplingParams,
    TenantConfig,
    TenantQuotaExceeded,
    compile_grammar,
    drain_engine,
    restore_engine,
)
from distributed_pytorch_tpu.training.lora import init_lora, merge_lora

VOCAB = 48


def tiny_lm():
    return TransformerLM(
        vocab_size=VOCAB, d_model=16, n_layers=1, n_heads=2, d_ff=32,
        dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def model_and_params():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


ENGINE_KW = dict(
    max_slots=4, max_seq_len=32, page_size=4, token_budget=32,
    max_prefill_chunk=8, debug=True,
)
P6 = SamplingParams(max_new_tokens=6)

PROMPTS = [[5, 7, 11, 2, 1], [6, 1, 9], [40, 41, 3], [3, 3, 3, 3, 8]]


def make_engine(model, params, **kw):
    opts = dict(ENGINE_KW)
    opts.update(kw)
    return InferenceEngine(model, params, **opts)


def polled_reference(model, params, prompts, params_list=None, mods=None,
                     **engine_kw):
    """Run prompts on a bare engine with poll() only; return token lists."""
    eng = make_engine(model, params, **engine_kw)
    n = len(prompts)
    plist = params_list or [P6] * n
    mlist = mods or [None] * n
    ids = [
        eng.submit(p, sp, mods=m)
        for p, sp, m in zip(prompts, plist, mlist)
    ]
    eng.run()
    out = [list(eng.requests[i].generated) for i in ids]
    eng.close()
    return out


class ManualClock:
    """Deterministic injectable clock for door + SLO tests."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# --------------------------------------------------------------- streaming


class TestStreaming:
    def test_streamed_tokens_bitwise_equal_polled(self, model_and_params):
        model, params = model_and_params
        ref = polled_reference(model, params, PROMPTS)

        eng = make_engine(model, params)
        door = FrontDoor(
            eng, tenants={"a": TenantConfig(weight=2.0), "b": TenantConfig()}
        )
        streams = [
            door.open_stream(p, t, params=P6)
            for p, t in zip(PROMPTS, ["a", "b", "a", "b"])
        ]
        got = [s.drain() for s in streams]
        assert got == ref
        assert [s.status for s in streams] == ["finished"] * 4
        assert door.registry.read_counter("finished_total") == 4
        assert door.registry.read_counter("admitted_total") == 4
        eng.close()

    def test_incremental_interleaved_consumption(self, model_and_params):
        """Round-robin single-token pulls across streams still deliver
        each request's full ordered sequence."""
        model, params = model_and_params
        ref = polled_reference(model, params, PROMPTS)
        eng = make_engine(model, params)
        door = FrontDoor(eng)
        streams = [door.open_stream(p, params=P6) for p in PROMPTS]
        got = [[] for _ in streams]
        live = set(range(len(streams)))
        while live:
            for i in sorted(live):
                try:
                    got[i].append(next(streams[i]))
                except StopIteration:
                    live.discard(i)
        assert got == ref
        eng.close()

    def test_door_off_matches_bare_engine(self, model_and_params):
        """The door with no mods, one tenant, and no quotas is a pure
        pass-through: same tokens, same engine-visible order."""
        model, params = model_and_params
        ref = polled_reference(model, params, PROMPTS)
        eng = make_engine(model, params)
        door = FrontDoor(eng)
        got = [door.open_stream(p, params=P6).drain() for p in PROMPTS]
        assert got == ref
        eng.close()

    def test_backpressure_bounds_backlog(self, model_and_params):
        model, params = model_and_params
        eng = make_engine(model, params)
        door = FrontDoor(eng, max_stream_buffer=2)
        stream = door.open_stream(PROMPTS[0], params=P6)
        # Pump without consuming: generation must stall at the buffer
        # cap instead of running to completion.
        for _ in range(40):
            door.pump()
        assert stream.backlog() <= 2
        assert door.registry.read_counter("backpressure_stalls_total") > 0
        # Consuming drains the backlog and finishes the request with the
        # exact reference tokens.
        ref = polled_reference(model, params, [PROMPTS[0]])
        assert stream.drain() == ref[0]
        eng.close()

    def test_stuck_stream_raises_instead_of_spinning(
        self, model_and_params
    ):
        """A stream blocked behind ANOTHER stream's unconsumed backlog
        fails fast with a diagnosis, not an infinite pump loop."""
        model, params = model_and_params
        eng = make_engine(model, params)
        door = FrontDoor(
            eng, max_stream_buffer=2, max_pumps_per_token=50
        )
        door.open_stream(PROMPTS[0], params=P6)  # the never-consumed hog
        victim = door.open_stream(PROMPTS[1], params=P6)
        # Consuming the victim eagerly: once the hog's unconsumed backlog
        # hits the cap the door stops stepping, the victim runs out of
        # committed tokens, and iteration must raise rather than spin.
        with pytest.raises(RuntimeError, match="backpressure"):
            for _ in range(20):
                next(victim)
        eng.close()


# ------------------------------------------------------------- cancellation


class TestCancellation:
    def test_cancel_mid_stream_frees_pages_counts_and_spares_others(
        self, model_and_params
    ):
        model, params = model_and_params
        ref = polled_reference(model, params, PROMPTS[:2])
        eng = make_engine(model, params)
        free0 = eng.allocator.num_free
        door = FrontDoor(eng)
        s0 = door.open_stream(PROMPTS[0], params=P6)
        s1 = door.open_stream(PROMPTS[1], params=P6)
        first = next(s0)
        assert first == ref[0][0]
        s0.cancel()
        assert s0.status == "cancelled"
        assert door.registry.read_counter("cancelled_by_client_total") == 1
        # Partial output stays drainable and is a prefix of the
        # uninterrupted reference; the survivor still gets everything.
        partial = [first] + s0.drain()
        assert partial == ref[0][: len(partial)]
        full1 = s1.drain()
        assert full1 == ref[1]
        door.drive()
        assert eng.allocator.num_free == free0, "cancelled pages leaked"
        eng.close()

    def test_cancel_queued_stream_never_reaches_engine(
        self, model_and_params
    ):
        model, params = model_and_params
        eng = make_engine(model, params, max_queue=2)
        door = FrontDoor(eng, max_inflight=1)
        s0 = door.open_stream(PROMPTS[0], params=P6)
        door.pump()
        s1 = door.open_stream(PROMPTS[1], params=P6)
        assert s1.req_id is None  # still at the door
        submitted_before = len(eng.requests)
        s1.cancel()
        assert s1.status == "cancelled"
        assert s1.drain() == []
        door.drive()
        assert len(eng.requests) == submitted_before
        assert door.registry.read_counter("cancelled_by_client_total") == 1
        s0.drain()
        eng.close()

    def test_cancel_is_idempotent(self, model_and_params):
        model, params = model_and_params
        eng = make_engine(model, params)
        door = FrontDoor(eng)
        s = door.open_stream(PROMPTS[0], params=P6)
        next(s)
        s.cancel()
        s.cancel()
        assert door.registry.read_counter("cancelled_by_client_total") == 1
        eng.close()


# ----------------------------------------------------------- fair share


class TestFairShare:
    def test_tenant_queue_quota(self, model_and_params):
        model, params = model_and_params
        eng = make_engine(model, params)
        door = FrontDoor(
            eng,
            tenants={"t": TenantConfig(max_queued=2)},
            max_inflight=1,
        )
        filler = door.open_stream(PROMPTS[0], "t")
        door.pump()  # admit the filler; the rest queue at the door
        door.open_stream(PROMPTS[1], "t")
        door.open_stream(PROMPTS[2], "t")
        with pytest.raises(TenantQuotaExceeded):
            door.open_stream(PROMPTS[3], "t")
        assert door.registry.read_counter("rejected_quota_total") == 1
        assert filler.drain()  # other streams still complete
        door.drive()
        eng.close()

    def test_undeclared_tenant_rejected(self, model_and_params):
        model, params = model_and_params
        eng = make_engine(model, params)
        door = FrontDoor(eng, tenants={"a": TenantConfig()})
        with pytest.raises(KeyError, match="undeclared"):
            door.open_stream(PROMPTS[0], "zz")
        eng.close()

    def test_weighted_admission_ratio_and_idle_redistribution(
        self, model_and_params
    ):
        """Stride scheduling under contention admits ~weight-ratio; when
        the heavy tenant idles, the light one takes every admission."""
        model, params = model_and_params
        eng = make_engine(model, params, max_queue=64)
        door = FrontDoor(
            eng,
            tenants={
                "heavy": TenantConfig(weight=3.0),
                "light": TenantConfig(weight=1.0),
            },
            max_inflight=1,
        )
        p = SamplingParams(max_new_tokens=2)
        prompt = [4, 5, 6]  # equal cost so the ratio is pure weights
        heavy = [door.open_stream(prompt, "heavy", params=p)
                 for _ in range(24)]
        light = [door.open_stream(prompt, "light", params=p)
                 for _ in range(24)]
        order = []
        while any(not s.done for s in heavy + light):
            door.pump()
            for name, streams in (("heavy", heavy), ("light", light)):
                for s in streams:
                    if s.req_id is not None and (name, id(s)) not in order:
                        order.append((name, id(s)))
        first16 = [name for name, _ in order[:16]]
        # 3:1 stride => 12 heavy / 4 light in any aligned window of 16.
        assert first16.count("heavy") == 12
        assert first16.count("light") == 4

        # Idle redistribution: heavy's queue is empty now; light alone
        # gets every admission with no stale-vtime penalty.
        tail = [door.open_stream(prompt, "light", params=p)
                for _ in range(4)]
        for s in tail:
            s.drain()
        assert all(s.done for s in tail)
        eng.close()

    def test_rate_limit_throttles_admission(self, model_and_params):
        model, params = model_and_params
        clock = ManualClock()
        eng = make_engine(model, params, max_queue=16)
        p = SamplingParams(max_new_tokens=2)
        cost = 3 + 2  # prompt + max_new
        door = FrontDoor(
            eng,
            tenants={
                "limited": TenantConfig(
                    rate_tokens_per_s=float(cost), burst_tokens=float(cost)
                ),
            },
            clock=clock,
        )
        streams = [door.open_stream([4, 5, 6], "limited", params=p)
                   for _ in range(3)]
        door.pump()
        # Burst covers exactly one request; the rest wait on refill.
        assert sum(s.req_id is not None for s in streams) == 1
        door.pump()
        assert sum(s.req_id is not None for s in streams) == 1
        clock.advance(1.0)  # refills exactly one request's worth
        door.pump()
        assert sum(s.req_id is not None for s in streams) == 2
        clock.advance(1.0)
        for s in streams:
            s.drain()
        eng.close()


# ------------------------------------------------------------- model mods


class TestStopSequences:
    def test_stop_sequence_truncates_at_reference_prefix(
        self, model_and_params
    ):
        model, params = model_and_params
        long_p = SamplingParams(max_new_tokens=10)
        [ref] = polled_reference(
            model, params, [PROMPTS[0]], params_list=[long_p]
        )
        # Stop on the first two generated tokens: the request must
        # finish right after emitting them.
        stop = SamplingParams(
            max_new_tokens=10,
            stop_sequences=(tuple(ref[:2]),),
        )
        eng = make_engine(model, params)
        door = FrontDoor(eng)
        got = door.open_stream(PROMPTS[0], params=stop).drain()
        assert got == ref[:2]
        eng.close()

    def test_unmatched_stop_sequence_changes_nothing(
        self, model_and_params
    ):
        model, params = model_and_params
        [ref] = polled_reference(model, params, [PROMPTS[1]])
        never = SamplingParams(
            max_new_tokens=6, stop_sequences=((VOCAB - 1, VOCAB - 1),)
        )
        [got] = polled_reference(
            model, params, [PROMPTS[1]], params_list=[never]
        )
        assert got == ref


class TestLogitBias:
    def test_zero_bias_is_bitwise_noop(self, model_and_params):
        model, params = model_and_params
        ref = polled_reference(model, params, PROMPTS)
        mods = [Mods(logit_bias={1: 0.0, 7: 0.0}) for _ in PROMPTS]
        got = polled_reference(model, params, PROMPTS, mods=mods)
        assert got == ref

    def test_large_bias_forces_token(self, model_and_params):
        model, params = model_and_params
        mods = [Mods(logit_bias={13: 1e9})]
        [got] = polled_reference(model, params, [PROMPTS[0]], mods=mods)
        assert got == [13] * 6

    def test_mixed_bias_and_clean_batch_parity(self, model_and_params):
        """Bias rows ride the async group: clean requests batched with a
        biased one keep their exact reference tokens."""
        model, params = model_and_params
        ref = polled_reference(model, params, PROMPTS)
        mods = [None, Mods(logit_bias={13: 1e9}), None, None]
        got = polled_reference(model, params, PROMPTS, mods=mods)
        assert got[0] == ref[0]
        assert got[2] == ref[2]
        assert got[3] == ref[3]
        assert got[1] == [13] * 6


class TestGrammar:
    def test_grammar_constrains_output(self, model_and_params):
        model, params = model_and_params
        # Exactly three tokens from {10, 11, 12}, then forced end.
        mods = [Mods(grammar="[10-12] [10-12] [10-12]")]
        p = SamplingParams(max_new_tokens=10)
        [got] = polled_reference(
            model, params, [PROMPTS[0]], params_list=[p], mods=mods
        )
        assert len(got) == 3
        assert all(t in (10, 11, 12) for t in got)

    def test_all_allowing_grammar_is_bitwise_noop(self, model_and_params):
        """A `.*`-style grammar admits every token at every step; the
        mask is all-zeros, so tokens match the unconstrained run even
        though the rows take the sync-dispatch path."""
        model, params = model_and_params
        ref = polled_reference(model, params, PROMPTS)
        mods = [Mods(grammar=". *") for _ in PROMPTS]
        got = polled_reference(model, params, PROMPTS, mods=mods)
        assert got == ref

    def test_grammar_stream_via_door(self, model_and_params):
        model, params = model_and_params
        eng = make_engine(model, params)
        door = FrontDoor(eng)
        dfa = compile_grammar("[20-30] [20-30]+", VOCAB)
        s = door.open_stream(
            PROMPTS[2],
            params=SamplingParams(max_new_tokens=5),
            mods=Mods(grammar="[20-30] [20-30]+"),
        )
        got = s.drain()
        state = 0
        for t in got:
            assert 20 <= t <= 30
            state = dfa.advance(state, t)
        eng.close()


class TestLoraMultiplex:
    def _adapters(self, params, seed, rank=2):
        """Random-B adapters (init_lora gives B=0, which would merge to
        the base model and prove nothing)."""
        ad = init_lora(params, rank, jax.random.PRNGKey(seed))
        return jax.tree_util.tree_map(
            lambda x: (
                jax.random.normal(
                    jax.random.PRNGKey(seed + 1), x.shape, x.dtype
                ) * 0.3
                if x.shape[0] == rank  # lora_b rows
                else x
            ),
            ad,
        )

    def test_multiplexed_batch_matches_solo_and_offline(
        self, model_and_params
    ):
        model, params = model_and_params
        ad1 = self._adapters(params, seed=7)
        ad2 = self._adapters(params, seed=19)

        def door_run(submissions):
            eng = make_engine(model, params)
            eng.register_adapter("a1", ad1, rank=2, alpha=4.0)
            eng.register_adapter("a2", ad2, rank=2, alpha=4.0)
            door = FrontDoor(eng)
            streams = [
                door.open_stream(p, params=P6, mods=m)
                for p, m in submissions
            ]
            out = [s.drain() for s in streams]
            eng.close()
            return out

        mixed = door_run([
            (PROMPTS[0], Mods(adapter="a1")),
            (PROMPTS[1], None),
            (PROMPTS[2], Mods(adapter="a2")),
            (PROMPTS[3], Mods(adapter="a1")),
        ])
        solo_a1 = door_run([(PROMPTS[0], Mods(adapter="a1"))])
        solo_base = door_run([(PROMPTS[1], None)])
        solo_a2 = door_run([(PROMPTS[2], Mods(adapter="a2"))])
        assert mixed[0] == solo_a1[0]
        assert mixed[1] == solo_base[0]
        assert mixed[2] == solo_a2[0]

        # ...and the adapter rows match the offline path under an
        # eagerly merged model: greedy continuous batching == generate().
        merged = merge_lora(params, ad1, rank=2, alpha=4.0)
        prompt = jnp.asarray([PROMPTS[0]], jnp.int32)
        offline = generate(model, merged, prompt, 6)
        assert mixed[0] == [int(t) for t in
                            np.asarray(offline)[0, len(PROMPTS[0]):]]

    def test_adapter_lru_eviction_and_remerge(self, model_and_params):
        model, params = model_and_params
        ad1 = self._adapters(params, seed=7)
        ad2 = self._adapters(params, seed=19)
        eng = make_engine(model, params, max_live_adapters=1)
        eng.register_adapter("a1", ad1, rank=2)
        eng.register_adapter("a2", ad2, rank=2)  # warm-merge evicts a1
        door = FrontDoor(eng)
        s1 = door.open_stream(PROMPTS[0], params=P6, mods=Mods(adapter="a1"))
        g1 = s1.drain()
        s2 = door.open_stream(PROMPTS[0], params=P6, mods=Mods(adapter="a2"))
        s2.drain()
        assert len(eng.adapters.live) <= 1
        assert eng.adapters.evictions >= 2
        # Re-using the evicted adapter re-merges to identical tokens.
        s3 = door.open_stream(PROMPTS[0], params=P6, mods=Mods(adapter="a1"))
        assert s3.drain() == g1
        assert eng.registry.read_counter("adapter_evictions_total") >= 3
        eng.close()

    def test_unknown_adapter_refused_at_submit(self, model_and_params):
        model, params = model_and_params
        eng = make_engine(model, params)
        with pytest.raises(KeyError):
            eng.submit(PROMPTS[0], P6, mods=Mods(adapter="nope"))
        eng.close()


class TestRecompileSafety:
    def test_sentinel_zero_under_mixed_mods_steady_state(
        self, model_and_params
    ):
        """The acceptance gate: after warmup, a mixed stream of clean /
        biased / grammar / adapter requests triggers ZERO fresh XLA
        compilations — mods are operands and params swaps, never shapes."""
        model, params = model_and_params
        eng = make_engine(model, params, xla_ledger=True)
        ad = TestLoraMultiplex()._adapters(params, seed=7)
        eng.register_adapter("a1", ad, rank=2)  # warm pre-arm
        door = FrontDoor(eng)

        def mix(i):
            return [
                None,
                Mods(logit_bias={7: 2.5}),
                Mods(grammar="[5-40]+"),
                Mods(adapter="a1"),
            ][i % 4]

        # Warm every group shape once.
        for i in range(4):
            door.open_stream(PROMPTS[i % 4], params=P6, mods=mix(i))
        door.drive()
        sentinel = eng.arm_recompile_sentinel()

        for i in range(8):
            door.open_stream(PROMPTS[i % 4], params=P6, mods=mix(i))
        door.drive()
        assert sentinel.count == 0, sentinel.trips
        assert eng.registry.read_counter("engine_recompiles_total") == 0
        eng.close()


# -------------------------------------------------------- burst isolation


def _poisson_arrivals(rng, rate_per_s, horizon_s):
    t, out = 0.0, []
    while True:
        t += rng.expovariate(rate_per_s)
        if t >= horizon_s:
            return out
        out.append(t)


@pytest.mark.chaos
class TestBurstIsolation:
    HORIZON = 30.0
    DT = 0.05  # fake seconds per pump
    QUIET_RATE = 1.0  # req/s; burst floods at 10x, beyond engine capacity
    P = SamplingParams(max_new_tokens=4)
    PROMPT = [4, 9, 2]

    def _run(self, model, params, burst_rate, *, tenants):
        """Open-loop run under a manual clock; returns per-tenant TTFT
        lists plus the door (for SLO inspection)."""
        clock = ManualClock()
        # Two slots: the 10x burst saturates the engine, which is the
        # whole point — isolation must come from the door, not headroom.
        eng = make_engine(model, params, max_queue=256, max_slots=2)
        door = FrontDoor(eng, tenants=tenants, clock=clock, max_inflight=3)
        arrivals = []
        rng = random.Random(1234)
        for t in _poisson_arrivals(rng, self.QUIET_RATE, self.HORIZON):
            arrivals.append((t, "quiet"))
        if burst_rate:
            rng2 = random.Random(987)
            for t in _poisson_arrivals(rng2, burst_rate, self.HORIZON):
                arrivals.append((t, "burst"))
        arrivals.sort()
        streams = {"quiet": [], "burst": []}
        i = 0
        while clock.t < self.HORIZON + 20.0:
            while i < len(arrivals) and arrivals[i][0] <= clock.t:
                tenant = arrivals[i][1]
                try:
                    streams[tenant].append(
                        door.open_stream(self.PROMPT, tenant, params=self.P)
                    )
                except TenantQuotaExceeded:
                    pass
                i += 1
            door.pump()
            clock.advance(self.DT)
            if i >= len(arrivals) and all(
                s.done for ss in streams.values() for s in ss
            ):
                break
        ttfts = {
            tenant: [
                s.first_token_t - s.submit_t
                for s in ss
                if s.first_token_t is not None
            ]
            for tenant, ss in streams.items()
        }
        eng.close()
        return ttfts, door

    def test_quiet_tenant_isolated_from_10x_burst(self, model_and_params):
        model, params = model_and_params
        quota = {"max_queued": 64}
        solo_tenants = {
            "quiet": TenantConfig(weight=1.0, **quota),
            "burst": TenantConfig(weight=1.0, **quota),
        }
        solo, _ = self._run(
            model, params, burst_rate=0.0, tenants=solo_tenants
        )
        solo_p95 = float(np.quantile(solo["quiet"], 0.95))

        # Calibrate the shared SLO threshold from the solo run: far above
        # anything fair share lets the quiet tenant see, far below what an
        # unthrottled 10x flood inflicts on itself (its queue backs up for
        # tens of fake seconds).
        slo = dict(ttft_slo_s=solo_p95 + 30 * self.DT)
        tenants = {
            "quiet": TenantConfig(weight=1.0, **quota, **slo),
            "burst": TenantConfig(weight=1.0, **quota, **slo),
        }
        mixed, door = self._run(
            model, params, burst_rate=10 * self.QUIET_RATE,
            tenants=tenants,
        )
        quiet_p95 = float(np.quantile(mixed["quiet"], 0.95))
        burst_p95 = float(np.quantile(mixed["burst"], 0.95))
        # Fair share holds the quiet tenant within a few admission/service
        # intervals of its solo latency (a request's service time is ~5
        # pumps) even while the other tenant floods 10x...
        assert quiet_p95 <= solo_p95 + 20 * self.DT, (
            f"quiet p95 {quiet_p95:.3f}s vs solo {solo_p95:.3f}s"
        )
        # ...while the burster pays for its own flood: its queue backs up
        # for many multiples of anything quiet experiences.
        assert burst_p95 >= 5.0 * quiet_p95, (
            f"burst p95 {burst_p95:.3f}s vs quiet {quiet_p95:.3f}s — the "
            "burst load never saturated; the isolation claim is vacuous"
        )
        # SLO asymmetry: the burster burns its own budget, not quiet's.
        assert door.registry.read_gauge("slo_ttft_quiet_firing") == 0.0
        assert door.registry.read_counter("slo_ttft_burst_alerts_total") >= 1


# ------------------------------------------------- drain-mid-stream resume


class TestDrainMidStream:
    def test_snapshot_carries_delivery_hwm_and_stream_resumes(
        self, model_and_params
    ):
        model, params = model_and_params
        [ref] = polled_reference(
            model, params, [PROMPTS[0]],
            params_list=[SamplingParams(max_new_tokens=8)],
        )

        eng = make_engine(model, params)
        door = FrontDoor(eng)
        stream = door.open_stream(
            PROMPTS[0], params=SamplingParams(max_new_tokens=8)
        )
        head = [next(stream) for _ in range(3)]
        assert head == ref[:3]

        snap = drain_engine(eng)
        rec = next(r for r in snap.requests)
        assert rec.delivered == 3  # the high-water mark rode the snapshot
        assert rec.tenant_id == "anon"
        eng.close()

        # Restore into a fresh engine; a fresh door adopts the live
        # request and resumes delivery at the recorded mark.
        eng2 = make_engine(model, params)
        restore_engine(eng2, snap)
        door2 = FrontDoor(eng2)
        adopted = door2.adopt_streams()
        assert len(adopted) == 1
        resumed = next(iter(adopted.values()))
        assert resumed.delivered == 3
        tail = resumed.drain()
        assert head + tail == ref, "replayed or skipped tokens"
        eng2.close()

    def test_snapshot_json_backcompat(self):
        """Old snapshot JSON (no tenant/delivered/stops/mods fields)
        still decodes, with the new fields at their defaults."""
        old = dict(
            req_id=5, prompt=[1, 2], generated=[3], max_new_tokens=4,
            temperature=0.0, seed=0, stop_token=None, deadline_s=None,
            metadata=None, preempt_count=0, age_s=0.5, ttft_s=0.1,
            kv_committed=2, trie_keys=[],
        )
        # The old dict must cover exactly the pre-frontdoor schema: every
        # REQUIRED field and none of the new defaulted ones (schema drift
        # here would mask a real wire break).
        required = {
            f.name
            for f in dataclasses.fields(RequestSnapshot)
            if f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING
        }
        assert set(old) == required
        rec = RequestSnapshot(**json.loads(json.dumps(old)))
        assert rec.tenant_id == "anon"
        assert rec.delivered == 0
        assert rec.stop_sequences == ()
        assert rec.mods is None


# ------------------------------------------------------------ fleet front


class TestRouterBackend:
    def _fleet(self, model, params, n=2):
        engines = [make_engine(model, params) for _ in range(n)]
        return FleetRouter(engines)

    def test_stream_and_cancel_through_router(self, model_and_params):
        model, params = model_and_params
        ref = polled_reference(model, params, PROMPTS[:3])
        router = self._fleet(model, params)
        door = FrontDoor(router, tenants={"a": TenantConfig()})
        streams = [
            door.open_stream(p, "a", params=P6) for p in PROMPTS[:3]
        ]
        assert next(streams[0]) == ref[0][0]
        streams[1].cancel()
        assert streams[1].status == "cancelled"
        got0 = [ref[0][0]] + streams[0].drain()
        got2 = streams[2].drain()
        assert got0 == ref[0]
        assert got2 == ref[2]
        assert door.registry.read_counter("cancelled_by_client_total") == 1
        router.close()
