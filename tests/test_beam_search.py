"""Beam search: the scores must be REAL log-probabilities of the returned
sequences (the per-step cache reorder is what could silently break that),
and wide-enough beams must find the global argmax sequence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.generation import beam_search, generate
from distributed_pytorch_tpu.models import TransformerLM

V = 8


def lm(**kw):
    cfg = dict(vocab_size=V, d_model=16, n_layers=2, n_heads=2, d_ff=32,
               dtype=jnp.float32)
    cfg.update(kw)
    return TransformerLM(**cfg)


def init(model, batch=2, seq=4, seed=0):
    tokens = np.random.default_rng(seed).integers(0, V, (batch, seq), np.int32)
    params = model.init(jax.random.PRNGKey(1), jnp.asarray(tokens))["params"]
    return params, tokens


def seq_logprob(model, params, full_tokens, prompt_len):
    """Full-forward summed next-token log-prob of the generated suffix —
    the ground truth the beam scores must equal."""
    logits = model.apply({"params": params}, jnp.asarray(full_tokens))
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    total = 0.0
    for t in range(prompt_len - 1, full_tokens.shape[1] - 1):
        total += float(logp[0, t, int(full_tokens[0, t + 1])])
    return total


class TestBeamSearch:
    def test_beam_one_equals_greedy(self):
        model = lm()
        params, tokens = init(model)
        ref = np.asarray(generate(model, params, jnp.asarray(tokens), 6))
        out, scores = beam_search(
            model, params, jnp.asarray(tokens), 6, beam_size=1
        )
        np.testing.assert_array_equal(np.asarray(out)[:, 0], ref)
        assert np.all(np.isfinite(np.asarray(scores)))

    def test_scores_are_true_sequence_logprobs(self):
        """Raw beam scores == full-forward summed log-probs of the returned
        sequences — pins the cache reorder end to end, for EVERY beam."""
        model = lm()
        params, tokens = init(model, batch=1)
        out, scores = beam_search(
            model, params, jnp.asarray(tokens), 5, beam_size=4
        )
        out, scores = np.asarray(out), np.asarray(scores)
        for k in range(4):
            want = seq_logprob(model, params, out[:1, k], tokens.shape[1])
            np.testing.assert_allclose(scores[0, k], want, atol=1e-4)

    def test_wide_beam_finds_global_argmax(self):
        """beam >= V^(new-1) holds every prefix, so the search is exhaustive
        and must return the brute-force best sequence with its exact
        score."""
        model = lm()
        params, tokens = init(model, batch=1, seq=3)
        new = 3
        prompt = jnp.asarray(tokens)
        out, scores = beam_search(
            model, params, prompt, new, beam_size=V ** (new - 1)
        )
        # Brute force over all V^new continuations via one batched forward.
        from itertools import product

        cands = np.array(list(product(range(V), repeat=new)), np.int32)
        full = np.concatenate(
            [np.tile(tokens, (len(cands), 1)), cands], axis=1
        )
        logits = model.apply({"params": params}, jnp.asarray(full))
        logp = np.asarray(
            jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        )
        t0 = tokens.shape[1] - 1
        totals = sum(
            logp[np.arange(len(cands)), t0 + i, full[:, t0 + i + 1]]
            for i in range(new)
        )
        best = int(np.argmax(totals))
        np.testing.assert_array_equal(
            np.asarray(out)[0, 0, tokens.shape[1]:], cands[best]
        )
        np.testing.assert_allclose(
            float(np.asarray(scores)[0, 0]), float(totals[best]), atol=1e-4
        )

    def test_sorted_and_distinct(self):
        model = lm()
        params, tokens = init(model, batch=2)
        out, scores = beam_search(
            model, params, jnp.asarray(tokens), 6, beam_size=4
        )
        s = np.asarray(scores)
        assert np.all(np.diff(s, axis=1) <= 1e-6)  # best-first
        o = np.asarray(out)
        # Beams of a row are distinct sequences (no duplicated-beam bug).
        for b in range(2):
            rows = {tuple(o[b, k]) for k in range(4)}
            assert len(rows) == 4

    def test_length_penalty_rescales(self):
        model = lm()
        params, tokens = init(model, batch=1)
        _, raw = beam_search(
            model, params, jnp.asarray(tokens), 5, beam_size=3
        )
        _, norm = beam_search(
            model, params, jnp.asarray(tokens), 5, beam_size=3,
            length_penalty=1.0,
        )
        np.testing.assert_allclose(
            np.asarray(norm), np.asarray(raw) / 5.0, rtol=1e-6
        )

    def test_dp_mesh_output_matches_single_device(self):
        """Beam search batch-sharded over a data mesh ([B*beam] dim
        P('data')) must reproduce the single-device tokens and scores."""
        from distributed_pytorch_tpu.parallel.mesh import make_mesh

        model = lm()
        params, tokens = init(model, batch=8)
        ref_t, ref_s = beam_search(
            model, params, jnp.asarray(tokens), 5, beam_size=4
        )
        mesh = make_mesh()
        out_t, out_s = beam_search(
            model, params, jnp.asarray(tokens), 5, beam_size=4, mesh=mesh
        )
        np.testing.assert_array_equal(np.asarray(out_t), np.asarray(ref_t))
        np.testing.assert_allclose(
            np.asarray(out_s), np.asarray(ref_s), rtol=1e-6
        )

    def test_composes_with_gqa_and_window(self):
        """The beam reorder gathers EVERY batch-leading cache leaf — GQA's
        reduced-head caches and windowed decode must compose unchanged
        (beam-1 == greedy is the exactness probe)."""
        model = lm(n_kv_heads=1, attention_window=3)
        params, tokens = init(model, batch=2, seq=5)
        ref = np.asarray(generate(model, params, jnp.asarray(tokens), 6))
        out, scores = beam_search(
            model, params, jnp.asarray(tokens), 6, beam_size=1
        )
        np.testing.assert_array_equal(np.asarray(out)[:, 0], ref)
        wide, _ = beam_search(
            model, params, jnp.asarray(tokens), 6, beam_size=3
        )
        assert np.all(np.isfinite(np.asarray(scores)))
        assert wide.shape == (2, 3, 11)

    def test_beam_size_validated(self):
        model = lm()
        params, tokens = init(model)
        with pytest.raises(ValueError, match="beam_size"):
            beam_search(model, params, jnp.asarray(tokens), 4, beam_size=0)
