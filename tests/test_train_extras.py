"""Tests for gradient accumulation, async checkpointing, and the replica
consistency checker."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_pytorch_tpu.checkpoint import AsyncCheckpointer, load_snapshot
from distributed_pytorch_tpu.models import MLP, ToyRegressor
from distributed_pytorch_tpu.parallel.consistency import (
    ReplicaDivergenceError,
    assert_replicas_consistent,
    check_device_replicas,
)
from distributed_pytorch_tpu.parallel.mesh import make_mesh
from distributed_pytorch_tpu.parallel.sharding import (
    put_global_batch,
    replicated_sharding,
)
from distributed_pytorch_tpu.training.losses import mse_loss
from distributed_pytorch_tpu.training.train_step import (
    create_train_state,
    make_train_step,
)
from distributed_pytorch_tpu.training.trainer import Trainer
from distributed_pytorch_tpu.utils.data import MaterializedDataset, ShardedLoader


def toy_batch(n=32, seed=0):
    rng = np.random.default_rng(seed)
    return (
        rng.standard_normal((n, 20)).astype(np.float32),
        rng.standard_normal((n, 1)).astype(np.float32),
    )


# ---------------------------------------------------------------- grad accum


@pytest.mark.parametrize("accum", [2, 4])
def test_grad_accum_matches_full_batch(accum):
    model = ToyRegressor()
    optimizer = optax.adam(1e-2)
    xs, ys = toy_batch()
    state_a = create_train_state(model, optimizer, xs, rng_seed=1)
    state_b = create_train_state(model, optimizer, xs, rng_seed=1)
    full = make_train_step(model.apply, optimizer, mse_loss)
    accum_step = make_train_step(model.apply, optimizer, mse_loss, grad_accum=accum)
    for _ in range(3):
        state_a, loss_a = full(state_a, (jnp.asarray(xs), jnp.asarray(ys)))
        state_b, loss_b = accum_step(state_b, (jnp.asarray(xs), jnp.asarray(ys)))
        np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7),
        state_a.params,
        state_b.params,
    )


def test_grad_accum_sharded():
    mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
    model = ToyRegressor()
    optimizer = optax.sgd(1e-2)
    xs, ys = toy_batch(n=32)
    state = create_train_state(model, optimizer, xs)
    state = jax.device_put(state, replicated_sharding(mesh))
    step = make_train_step(model.apply, optimizer, mse_loss, mesh=mesh, grad_accum=2)
    serial_state = create_train_state(model, optimizer, xs)
    serial = make_train_step(model.apply, optimizer, mse_loss)
    state, loss = step(state, put_global_batch(mesh, (xs, ys)))
    serial_state, serial_loss = serial(serial_state, (jnp.asarray(xs), jnp.asarray(ys)))
    np.testing.assert_allclose(float(loss), float(serial_loss), rtol=1e-6)


def test_grad_accum_indivisible_raises():
    model = ToyRegressor()
    xs, ys = toy_batch(n=30)
    state = create_train_state(model, optax.sgd(1e-2), xs)
    step = make_train_step(model.apply, optax.sgd(1e-2), mse_loss, grad_accum=4)
    with pytest.raises(ValueError, match="divisible"):
        step(state, (jnp.asarray(xs), jnp.asarray(ys)))


# ---------------------------------------------------------------- async ckpt


def test_async_checkpointer_roundtrip(tmp_path):
    model = ToyRegressor()
    xs, _ = toy_batch()
    state = create_train_state(model, optax.adam(1e-3), xs)
    path = str(tmp_path / "snap.npz")
    ck = AsyncCheckpointer()
    ck.save(path, state, metadata={"epochs_run": 7})
    ck.wait()
    restored, meta = load_snapshot(path, state)
    assert meta["epochs_run"] == 7
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        state.params,
        restored.params,
    )


def test_async_checkpointer_snapshot_is_of_save_time_state(tmp_path):
    """Mutating state after save() must not leak into the written file —
    the host gather happens at save() time."""
    model = ToyRegressor()
    xs, ys = toy_batch()
    state = create_train_state(model, optax.sgd(1e-1), xs)
    step = make_train_step(model.apply, optax.sgd(1e-1), mse_loss)
    path = str(tmp_path / "snap.npz")
    ck = AsyncCheckpointer()
    saved_kernel = np.asarray(state.params["linear"]["kernel"]).copy()
    ck.save(path, state, metadata={"epochs_run": 1})
    for _ in range(5):  # keep training while the write is in flight
        state, _ = step(state, (jnp.asarray(xs), jnp.asarray(ys)))
    ck.wait()
    restored, _ = load_snapshot(path, state)
    np.testing.assert_array_equal(
        np.asarray(restored.params["linear"]["kernel"]), saved_kernel
    )


def test_trainer_async_save_resume(tmp_path):
    data = MaterializedDataset(128)
    snap = str(tmp_path / "s.npz")

    def build():
        loader = ShardedLoader(data, 32)
        return Trainer(
            ToyRegressor(), loader, optax.sgd(1e-3), save_every=1,
            snapshot_path=snap, async_save=True, paranoid=True,
        )

    build().train(2)
    assert os.path.exists(snap)
    t2 = build()
    assert t2.epochs_run == 2  # resumed from the async-written snapshot


# ------------------------------------------------------------- consistency


def test_consistent_state_passes():
    mesh = make_mesh({"data": 8})
    model = MLP()
    xs, _ = toy_batch()
    state = create_train_state(model, optax.adam(1e-3), xs)
    state = jax.device_put(state, replicated_sharding(mesh))
    assert_replicas_consistent(state)


def test_divergent_device_replicas_detected():
    mesh = make_mesh({"data": 8})
    sharding = replicated_sharding(mesh)
    shape = (4, 4)
    # Hand-build a "replicated" array whose per-device buffers DISAGREE.
    buffers = [
        jax.device_put(
            np.full(shape, float(i == 3), np.float32), d
        )
        for i, d in enumerate(mesh.devices.flat)
    ]
    evil = jax.make_array_from_single_device_arrays(shape, sharding, buffers)
    with pytest.raises(ReplicaDivergenceError, match="replicated"):
        check_device_replicas({"w": evil})


def test_consistency_check_skips_sharded_leaves():
    """assert_replicas_consistent must tolerate deliberately sharded state
    (Trainer(partition_specs=...)): sharded leaves are excluded from the
    checksum (their local data legitimately differs per process), replicated
    leaves still checked."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_pytorch_tpu.parallel.consistency import (
        assert_replicas_consistent,
        tree_checksum,
    )
    from distributed_pytorch_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"data": 8})
    tree = {
        "replicated": jax.device_put(
            jnp.ones((8, 4)), NamedSharding(mesh, P())
        ),
        "sharded": jax.device_put(
            jnp.arange(16.0).reshape(8, 2), NamedSharding(mesh, P("data"))
        ),
    }
    assert_replicas_consistent(tree, name="mixed")  # must not raise
    assert len(tree_checksum(tree)) == 1  # only the replicated leaf counted


class TestLabelSmoothing:
    def test_zero_smoothing_equals_sparse_loss(self):
        from distributed_pytorch_tpu.training.losses import (
            smoothed_cross_entropy_loss,
            softmax_cross_entropy_loss,
        )

        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.standard_normal((8, 10)), jnp.float32)
        targets = jnp.asarray(rng.integers(0, 10, (8,)), jnp.int32)
        a = smoothed_cross_entropy_loss(0.0)(logits, targets)
        b = softmax_cross_entropy_loss(logits, targets)
        np.testing.assert_allclose(float(a), float(b), rtol=1e-6)

    def test_smoothing_matches_manual_mixture(self):
        from distributed_pytorch_tpu.training.losses import (
            smoothed_cross_entropy_loss,
        )

        rng = np.random.default_rng(1)
        logits = jnp.asarray(rng.standard_normal((4, 6)), jnp.float32)
        targets = jnp.asarray([0, 2, 5, 1], jnp.int32)
        eps, k = 0.1, 6
        soft = jax.nn.one_hot(targets, k) * (1 - eps) + eps / k
        import optax as _optax

        ref = jnp.mean(_optax.softmax_cross_entropy(logits, soft))
        got = smoothed_cross_entropy_loss(eps)(logits, targets)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)

    def test_rejects_bad_smoothing(self):
        from distributed_pytorch_tpu.training.losses import (
            smoothed_cross_entropy_loss,
        )

        for bad in (-0.1, 1.0, 1.5):
            with pytest.raises(ValueError, match="smoothing"):
                smoothed_cross_entropy_loss(bad)

    def test_drops_into_train_step(self):
        import optax

        from distributed_pytorch_tpu.training.losses import (
            smoothed_cross_entropy_loss,
        )
        from distributed_pytorch_tpu.training.train_step import (
            create_train_state,
            make_train_step,
        )

        model = MLP(hidden=(32,), features=4)
        rng = np.random.default_rng(0)
        xs = jnp.asarray(rng.standard_normal((32, 20)), jnp.float32)
        ys = jnp.asarray(rng.integers(0, 4, (32,)), jnp.int32)
        opt = optax.adam(1e-2)
        state = create_train_state(model, opt, xs)
        step = make_train_step(
            model.apply, opt, smoothed_cross_entropy_loss(0.1)
        )
        first = last = None
        for _ in range(15):
            state, loss = step(state, (xs, ys))
            first = first if first is not None else float(loss)
            last = float(loss)
        assert last < first

    def test_registers_exact_eval_twin(self):
        from distributed_pytorch_tpu.training.losses import (
            PER_SAMPLE_TWINS,
            smoothed_cross_entropy_loss,
        )

        loss_fn = smoothed_cross_entropy_loss(0.1)
        assert loss_fn in PER_SAMPLE_TWINS
        rng = np.random.default_rng(2)
        logits = jnp.asarray(rng.standard_normal((6, 5)), jnp.float32)
        targets = jnp.asarray(rng.integers(0, 5, (6,)), jnp.int32)
        per = PER_SAMPLE_TWINS[loss_fn](logits, targets)
        assert per.shape == (6,)
        np.testing.assert_allclose(
            float(jnp.mean(per)), float(loss_fn(logits, targets)), rtol=1e-6
        )
