"""Observability-wire tests: the per-engine introspection server, the
strict Prometheus text-format grammar checker, device-truth XLA program
accounting, the recompile sentinel, and the fleet tooling riding the wire
(``obs_top`` rendering, ``bench_history`` gating).

The invariant under test throughout: observability OFF keeps the fast
path; observability ON (server scraped from another thread mid-run,
ledger, armed sentinel) keeps greedy tokens bitwise-identical.
"""

import json
import socket
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.generation import generate
from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.obs import (
    ExpositionError,
    FlightRecorder,
    MetricsRegistry,
    Tracer,
    validate_exposition,
)
from distributed_pytorch_tpu.obs.server import scrape
from distributed_pytorch_tpu.serving import InferenceEngine, SamplingParams


def tiny_lm(**kw):
    return TransformerLM(
        vocab_size=48, d_model=16, n_layers=2, n_heads=2, d_ff=32,
        dtype=jnp.float32, **kw,
    )


@pytest.fixture(scope="module")
def model_and_params():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def make_engine(model, params, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("page_size", 4)
    kw.setdefault("token_budget", 16)
    kw.setdefault("max_prefill_chunk", 8)
    return InferenceEngine(model, params, **kw)


def offline_greedy(model, params, prompt, max_new):
    out = generate(
        model, params, jnp.asarray([prompt], jnp.int32),
        max_new_tokens=max_new, temperature=0.0, rng=jax.random.PRNGKey(0),
    )
    return np.asarray(out)[0, len(prompt):].tolist()


# ------------------------------------------------- prometheus text grammar


GOOD = (
    "# HELP engine_steps_total engine steps\n"
    "# TYPE engine_steps_total counter\n"
    "engine_steps_total 42\n"
    "# HELP queue_depth requests waiting\n"
    "# TYPE queue_depth gauge\n"
    "queue_depth 3\n"
    "# HELP ttft_seconds ttft\n"
    "# TYPE ttft_seconds summary\n"
    'ttft_seconds{quantile="0.5"} 0.01\n'
    'ttft_seconds{quantile="0.99"} 0.05\n'
    "ttft_seconds_sum 1.5\n"
    "ttft_seconds_count 100\n"
)


class TestPromTextGrammar:
    def test_valid_document_parses(self):
        fams = validate_exposition(GOOD)
        assert set(fams) == {
            "engine_steps_total", "queue_depth", "ttft_seconds"
        }
        assert fams["engine_steps_total"].type == "counter"
        assert fams["ttft_seconds"].type == "summary"
        # quantile samples + _sum + _count all land in the summary family
        assert len(fams["ttft_seconds"].samples) == 4

    def test_missing_trailing_newline(self):
        with pytest.raises(ExpositionError, match="newline"):
            validate_exposition(GOOD.rstrip("\n"))

    def test_sample_without_type(self):
        with pytest.raises(ExpositionError):
            validate_exposition("loose_metric 1\n")

    def test_help_after_type_rejected(self):
        bad = (
            "# HELP x help\n"
            "# TYPE x counter\n"
            "# HELP x late help\n"
            "x 1\n"
        )
        with pytest.raises(ExpositionError):
            validate_exposition(bad)

    def test_family_must_be_contiguous(self):
        bad = (
            "# HELP a a\n# TYPE a counter\na 1\n"
            "# HELP b b\n# TYPE b counter\nb 2\n"
            "a 3\n"  # reopens a closed family
        )
        with pytest.raises(ExpositionError):
            validate_exposition(bad)

    def test_bad_metric_name(self):
        with pytest.raises(ExpositionError):
            validate_exposition("# HELP 9bad x\n# TYPE 9bad counter\n9bad 1\n")

    def test_reserved_label_name(self):
        bad = '# HELP x x\n# TYPE x counter\nx{__secret="1"} 1\n'
        with pytest.raises(ExpositionError):
            validate_exposition(bad)

    def test_duplicate_label_name(self):
        bad = '# HELP x x\n# TYPE x counter\nx{a="1",a="2"} 1\n'
        with pytest.raises(ExpositionError):
            validate_exposition(bad)

    def test_bad_escape_in_label_value(self):
        bad = '# HELP x x\n# TYPE x counter\nx{a="tab\\t"} 1\n'
        with pytest.raises(ExpositionError):
            validate_exposition(bad)

    def test_legal_escapes_parse(self):
        ok = (
            "# HELP x x\n# TYPE x counter\n"
            'x{a="q\\"uote",b="back\\\\slash",c="new\\nline"} 1\n'
        )
        fams = validate_exposition(ok)
        labels = fams["x"].samples[0][1]
        assert labels["a"] == 'q"uote'
        assert labels["b"] == "back\\slash"
        assert labels["c"] == "new\nline"

    def test_counter_rejects_suffixed_sample(self):
        bad = "# HELP x x\n# TYPE x counter\nx 1\nx_sum 2\n"
        with pytest.raises(ExpositionError):
            validate_exposition(bad)

    def test_summary_rejects_bucket(self):
        bad = "# HELP x x\n# TYPE x summary\nx_bucket 1\n"
        with pytest.raises(ExpositionError):
            validate_exposition(bad)

    def test_bad_float_value(self):
        with pytest.raises(ExpositionError):
            validate_exposition("# HELP x x\n# TYPE x gauge\nx notanumber\n")

    def test_special_float_values(self):
        ok = (
            "# HELP x x\n# TYPE x gauge\n"
            'x{k="a"} NaN\nx{k="b"} +Inf\n'
        )
        validate_exposition(ok)

    def test_live_registry_output_is_valid(self):
        reg = MetricsRegistry(namespace="t")
        reg.counter_fn("events_total", lambda: 7)
        reg.gauge_fn("depth", lambda: 2.5)
        fams = validate_exposition(reg.prometheus_text())
        assert "t_events_total" in fams and "t_depth" in fams


# --------------------------------------------------------- server endpoints


@pytest.fixture(scope="class")
def served_engine(model_and_params):
    """One engine + running server shared across the read-only endpoint
    tests (compiles once; every test only GETs)."""
    model, params = model_and_params
    eng = make_engine(
        model, params, tracer=Tracer(), flight=FlightRecorder(capacity=256),
        xla_ledger=True,
    )
    rid = eng.submit([1, 2, 3, 4, 5], SamplingParams(max_new_tokens=4))
    eng.run()
    assert eng.poll(rid).finished
    server = eng.serve()
    yield eng, server
    eng.close()


class TestIntrospectionServer:
    def test_serve_is_idempotent(self, served_engine):
        eng, server = served_engine
        assert eng.serve() is server

    def test_metrics_valid_under_strict_grammar(self, served_engine):
        _eng, server = served_engine
        body = scrape(server.url, "/metrics")
        fams = validate_exposition(body)
        assert "serving_engine_steps_total" in fams
        assert "serving_ttft_seconds" in fams
        assert "serving_xla_programs" in fams
        assert "serving_engine_recompiles_total" in fams

    def test_healthz_live(self, served_engine):
        _eng, server = served_engine
        with urllib.request.urlopen(server.url + "/healthz") as resp:
            assert resp.status == 200
            assert json.loads(resp.read())["status"] == "live"

    def test_statusz_shape(self, served_engine):
        eng, server = served_engine
        doc = scrape(server.url, "/statusz")
        for key in (
            "health", "engine", "queue_depth", "running_requests",
            "requests", "pages", "admission", "latency", "xla",
            "recompile_sentinel",
        ):
            assert key in doc, key
        assert doc["health"] == "live"
        assert doc["engine"]["steps"] == eng.metrics.engine_steps
        names = {p["name"] for p in doc["xla"]["programs"]}
        assert "decode_step" in names

    def test_trace_and_postmortem_served(self, served_engine):
        _eng, server = served_engine
        trace = scrape(server.url, "/trace")
        assert "traceEvents" in trace
        post = scrape(server.url, "/postmortem")
        assert post["reason"] == "postmortem_endpoint"

    def test_index_and_404(self, served_engine):
        _eng, server = served_engine
        index = scrape(server.url, "/")
        assert "/metrics" in index["endpoints"]
        with pytest.raises(urllib.error.HTTPError):
            scrape(server.url, "/nope")

    def test_snapshot_roundtrip_renders_valid_text(self, served_engine):
        eng, server = served_engine
        snap = scrape(server.url, "/snapshot")
        text = MetricsRegistry.render_snapshot(snap)
        fams = validate_exposition(text)
        assert "serving_engine_steps_total" in fams


class TestHealthTransitions:
    def test_live_draining_closed(self, model_and_params):
        model, params = model_and_params
        eng = make_engine(model, params)
        server = eng.serve()
        assert scrape(server.url, "/healthz")["status"] == "live"
        eng.stop_admission()
        # scrape() treats the 503 as an answer, not an error
        assert scrape(server.url, "/healthz")["status"] == "draining"
        assert eng.health() == "draining"
        url = server.url
        eng.close()  # stops the server too
        assert eng.health() == "closed"
        with pytest.raises(Exception):
            urllib.request.urlopen(url + "/healthz", timeout=1)


class TestServerParity:
    def test_tokens_identical_with_server_scraped_mid_run(
        self, model_and_params
    ):
        """The acceptance criterion: a server attached and hammered from
        another thread while the engine steps changes nothing about the
        greedy token streams."""
        model, params = model_and_params
        prompts = [[1, 2, 3], [7, 5, 4, 6], [9, 8], [3, 1, 4, 1, 5]]
        refs = [offline_greedy(model, params, p, 6) for p in prompts]

        eng = make_engine(model, params, xla_ledger=True)
        server = eng.serve()
        stop = threading.Event()
        seen = {"n": 0, "errors": 0}

        def hammer():
            # Generous timeout: a step that hits an XLA compile holds the
            # registry lock for seconds, and a scrape must WAIT there (that
            # blocking is the consistency guarantee), not error out.
            while not stop.is_set():
                try:
                    validate_exposition(
                        scrape(server.url, "/metrics", timeout=60.0)
                    )
                    scrape(server.url, "/statusz", timeout=60.0)
                    seen["n"] += 1
                except Exception:
                    seen["errors"] += 1

        thread = threading.Thread(target=hammer, daemon=True)
        thread.start()
        try:
            ids = [
                eng.submit(p, SamplingParams(max_new_tokens=6))
                for p in prompts
            ]
            eng.run()
            got = [eng.poll(r).generated for r in ids]
        finally:
            stop.set()
            thread.join(timeout=10)
        assert got == refs
        assert seen["n"] > 0 and seen["errors"] == 0
        eng.close()


class TestMergeRemote:
    def test_two_engines_aggregate_over_http(self, model_and_params):
        model, params = model_and_params
        engines = [make_engine(model, params) for _ in range(2)]
        servers = [eng.serve() for eng in engines]
        try:
            for eng in engines:
                rid = eng.submit(
                    [1, 2, 3], SamplingParams(max_new_tokens=3)
                )
                eng.run()
                assert eng.poll(rid).finished
            merged = MetricsRegistry.merge_remote(
                [srv.url for srv in servers]
            )
            total = sum(
                eng.metrics.tokens_generated for eng in engines
            )
            assert merged["counters"]["serving_tokens_generated_total"] == (
                total
            )
            text = MetricsRegistry.render_snapshot(merged)
            fams = validate_exposition(text)
            assert float(
                fams["serving_tokens_generated_total"].samples[0][2]
            ) == float(total)
            # reservoirs merge exactly: sample counts add across engines
            n_ttft = sum(eng.metrics.ttft.count for eng in engines)
            count = [
                float(val)
                for name, _labels, val in fams["serving_ttft_seconds"].samples
                if name.endswith("_count")
            ]
            assert count == [float(n_ttft)]
        finally:
            for eng in engines:
                eng.close()


# --------------------------------------------- xla ledger + recompile watch


class TestProgramLedger:
    def test_device_truth_recorded(self, model_and_params):
        model, params = model_and_params
        eng = make_engine(model, params, xla_ledger=True)
        rid = eng.submit([3, 1, 4, 1, 5], SamplingParams(max_new_tokens=4))
        eng.run()
        assert eng.poll(rid).finished
        names = {name for (name, _sig) in eng.xla.programs}
        assert "decode_step" in names
        assert any(n.startswith("prefill_step_c") for n in names)
        for rec in eng.xla.programs.values():
            assert rec.compile_seconds > 0
            assert rec.calls >= 1
        decode = next(
            rec for (name, _), rec in eng.xla.programs.items()
            if name == "decode_step"
        )
        assert decode.flops and decode.flops > 0
        assert decode.argument_bytes > 0
        # transfers were counted both ways, live bytes tracked
        assert eng.xla.bytes_h2d_total > 0 and eng.xla.bytes_d2h_total > 0
        assert eng.xla.live_bytes > 0
        meta = eng.xla.metadata()
        assert meta["bytes_h2d_total"] == eng.xla.bytes_h2d_total
        assert len(meta["programs"]) == len(eng.xla.programs)
        eng.close()

    def test_ledger_off_is_fast_path(self, model_and_params):
        model, params = model_and_params
        eng = make_engine(model, params)
        assert eng.xla is None and eng.sentinel is None
        with pytest.raises(RuntimeError, match="xla_ledger"):
            eng.arm_recompile_sentinel()
        eng.close()


class TestRecompileSentinel:
    def test_zero_at_steady_state_and_trip_on_new_shape(
        self, model_and_params
    ):
        model, params = model_and_params
        eng = make_engine(
            model, params, flight=FlightRecorder(capacity=256),
            xla_ledger=True,
        )
        # Warm: decode + prefill chunks for short prompts.
        warm = eng.submit([1, 2, 3], SamplingParams(max_new_tokens=3))
        eng.run()
        assert eng.poll(warm).finished
        sentinel = eng.arm_recompile_sentinel()
        assert sentinel.armed

        # Steady state: same shapes, zero trips across the whole run.
        rid = eng.submit([4, 5, 6], SamplingParams(max_new_tokens=3))
        eng.run()
        assert eng.poll(rid).finished
        assert sentinel.count == 0
        assert not sentinel.firing

        # A prompt long enough to need a never-seen prefill chunk forces
        # a fresh XLA compile: exactly what the sentinel exists to catch.
        big = eng.submit(
            list(range(1, 14)), SamplingParams(max_new_tokens=2)
        )
        eng.run()
        assert eng.poll(big).finished
        assert sentinel.count >= 1
        assert sentinel.firing
        assert any(
            "prefill" in trip["program"] for trip in sentinel.trips
        )
        # ...and the trip is on the record everywhere it should be:
        assert eng.registry.read_counter("engine_recompiles_total") == (
            sentinel.count
        )
        events = [
            ev for ev in eng.flight.events() if ev["kind"] == "recompile"
        ]
        assert len(events) == sentinel.count
        status = sentinel.status()
        assert status["firing"] and status["count"] == sentinel.count
        sentinel.acknowledge()
        assert not sentinel.firing and sentinel.count >= 1
        eng.close()
        assert not sentinel.armed  # close() disarms


# ------------------------------------------------------------ obs_top tool


class TestObsTop:
    STATUS = {
        "health": "live",
        "queue_depth": 2,
        "running_requests": 3,
        "pages": {
            "pages_free": 10, "pages_referenced": 5, "pages_cached_idle": 1,
        },
        "latency": {
            "ttft_p50_s": 0.012, "tpot_p50_s": 0.0015,
            "tpot_p95_s": 0.002, "tokens_per_sec": 123.4,
        },
        "recompile_sentinel": {"count": 1, "firing": True},
        "slo": {"firing": ["ttft_p95"]},
        "requests": [
            {
                "req_id": 7, "phase": "decoding", "slot": 0, "age_s": 1.5,
                "prompt_len": 30, "len_cached": 24, "generated": 9,
                "preempt_count": 0,
            },
        ],
    }

    def test_render_frame_plain(self):
        from tools.obs_top import render_frame

        frame = render_frame(
            [("http://e1:80", self.STATUS), ("http://e2:80", None)],
            color=False,
        )
        assert "e1:80" in frame and "e2:80" in frame
        assert "live" in frame and "down" in frame
        assert "10/5/1" in frame  # pages free/ref/idle
        assert "ttft_p95" in frame  # firing SLO surfaces by name
        assert "decoding" in frame  # request table rendered
        assert "\x1b" not in frame  # no ANSI in plain mode

    def test_render_frame_handles_empty_latency(self):
        from tools.obs_top import render_frame

        doc = {"health": "live", "queue_depth": 0, "running_requests": 0}
        frame = render_frame([("http://e:80", doc)], color=False)
        assert "live" in frame

    def test_render_frame_host_tier_columns(self):
        from tools.obs_top import render_frame

        doc = dict(self.STATUS)
        doc["hostkv"] = {
            "hostkv_pages_resident": 7,
            "hostkv_pages_capacity": 48,
        }
        # Cumulative spill counter climbing 4096 B/s; no fetch series yet
        # (the fetch cell must degrade to '-' like any missing series).
        ts = {
            "series": {
                "serving_hostkv_spill_bytes_total": {
                    "kind": "counter",
                    "points": [[0.0, 0.0], [1.0, 4096.0], [2.0, 12288.0]],
                },
            }
        }
        frame = render_frame(
            [("http://e1:80", doc)],
            color=False,
            timeseries={"http://e1:80": ts},
        )
        assert "HOST r/c" in frame and "7/48" in frame
        assert "SPILL B/s" in frame and "FETCH B/s" in frame
        # The rate sparkline renders deltas, so the climbing counter shows
        # two cells (4096 then 8192 B/s), not a monotone ramp of totals.
        lines = frame.splitlines()
        row = next(ln for ln in lines if "e1:80" in ln)
        assert "▁" in row and "█" in row  # distinct rate levels rendered

    def test_render_frame_without_host_tier_shows_dash(self):
        from tools.obs_top import render_frame

        frame = render_frame([("http://e1:80", self.STATUS)], color=False)
        row = next(
            ln for ln in frame.splitlines() if "e1:80" in ln
        )
        assert " - " in row  # HOST r/c cell degrades to '-'


# ------------------------------------------------------- bench history gate


class TestBenchHistory:
    def _bench(self, tps=100.0, tpot=0.002, device="cpu"):
        return {
            "platform": "cpu",
            "device_kind": device,
            "rows": [
                {
                    "prefix_caching": True,
                    "speculative": False,
                    "stats": {
                        "tokens_per_sec": tps,
                        "tpot_s_p50": tpot,
                        "ttft_s_p50": 0.01,
                        "requests_completed": 24,
                    },
                },
            ],
            "obs": {"recompiles_at_steady_state": 0},
        }

    def test_extract_row_shape(self):
        from tools.bench_history import extract_row

        row = extract_row(self._bench())
        assert "prefix=on,spec=off" in row["configs"]
        cfg = row["configs"]["prefix=on,spec=off"]
        assert cfg["tokens_per_sec"] == 100.0
        assert row["obs"]["recompiles_at_steady_state"] == 0
        assert row["recorded_at"]

    def test_within_tolerance_passes(self):
        from tools.bench_history import compare_rows, extract_row

        prev = extract_row(self._bench(tps=100.0, tpot=0.002))
        cur = extract_row(self._bench(tps=95.0, tpot=0.0021))
        assert compare_rows(prev, cur) == []

    def test_throughput_drop_fails(self):
        from tools.bench_history import compare_rows, extract_row

        prev = extract_row(self._bench(tps=100.0))
        cur = extract_row(self._bench(tps=85.0))
        failures = compare_rows(prev, cur)
        assert len(failures) == 1 and "tokens_per_sec" in failures[0]

    def test_tpot_rise_fails(self):
        from tools.bench_history import compare_rows, extract_row

        prev = extract_row(self._bench(tpot=0.002))
        cur = extract_row(self._bench(tpot=0.0023))
        failures = compare_rows(prev, cur)
        assert len(failures) == 1 and "tpot_s_p50" in failures[0]

    def test_device_kind_change_voids_gate(self):
        from tools.bench_history import compare_rows, extract_row

        prev = extract_row(self._bench(tps=100.0, device="cpu"))
        cur = extract_row(self._bench(tps=10.0, device="TPU v4"))
        assert compare_rows(prev, cur) == []

    def test_new_config_has_no_baseline(self):
        from tools.bench_history import compare_rows, extract_row

        prev = extract_row(self._bench())
        cur_doc = self._bench(tps=1.0)
        cur_doc["rows"][0]["speculative"] = True  # different config key
        cur = extract_row(cur_doc)
        assert compare_rows(prev, cur) == []


class TestScrapeHardening:
    """scrape() must never wedge its caller: a peer that accepts the TCP
    connection and then never answers — the classic half-dead replica —
    has to raise within the configured timeout budget, and transient
    transport blips get exactly the bounded retry, nothing more."""

    @staticmethod
    def _black_hole():
        """A socket that accepts (kernel backlog) and never responds."""
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(5)
        return srv

    def test_accept_but_never_respond_raises_bounded(self):
        srv = self._black_hole()
        url = f"http://127.0.0.1:{srv.getsockname()[1]}"
        try:
            t0 = time.monotonic()
            with pytest.raises(OSError):
                scrape(url, "/snapshot", timeout=0.2, retries=1,
                       backoff_s=0.05)
            elapsed = time.monotonic() - t0
            # (retries+1) * timeout + backoff, with generous slack — the
            # point is "seconds, not forever".
            assert elapsed < 3.0
        finally:
            srv.close()

    def test_merge_remote_dead_peer_raises_bounded(self):
        srv = self._black_hole()
        url = f"http://127.0.0.1:{srv.getsockname()[1]}"
        try:
            t0 = time.monotonic()
            with pytest.raises(OSError):
                MetricsRegistry.merge_remote(
                    [url], timeout=0.2, retries=1, backoff_s=0.05
                )
            assert time.monotonic() - t0 < 3.0
        finally:
            srv.close()

    def test_retry_recovers_after_transport_blip(self):
        """First connection reset before any response; the bounded retry
        lands on a healthy answer."""
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(5)
        url = f"http://127.0.0.1:{srv.getsockname()[1]}"

        def serve():
            conn, _ = srv.accept()
            conn.close()  # blip: reset with no HTTP response
            conn, _ = srv.accept()
            conn.recv(65536)
            body = b'{"ok": true}'
            conn.sendall(
                b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                b"\r\n" + body
            )
            conn.close()

        thread = threading.Thread(target=serve, daemon=True)
        thread.start()
        try:
            doc = scrape(url, "/snapshot", timeout=2.0, retries=1,
                         backoff_s=0.01)
            assert doc == {"ok": True}
        finally:
            thread.join(timeout=5)
            srv.close()

    def test_http_error_is_answered_not_retried(self, served_engine):
        """A served error page comes from a live server: no retry, and
        /healthz 503 still returns its JSON verdict."""
        _, server = served_engine
        with pytest.raises(urllib.error.HTTPError):
            scrape(server.url, "/nope", retries=3)
