"""train_step unit tests: loss decreases, gradients correct, DP == serial.

Mirrors SURVEY.md §4's designed strategy (the reference has no tests at all).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_pytorch_tpu.models.toy import ToyRegressor
from distributed_pytorch_tpu.parallel.mesh import make_mesh
from distributed_pytorch_tpu.parallel.sharding import put_global_batch, replicated_sharding
from distributed_pytorch_tpu.training.losses import mse_loss
from distributed_pytorch_tpu.training.train_step import (
    create_train_state,
    make_train_step,
)
from distributed_pytorch_tpu.utils.data import MaterializedDataset, ShardedLoader


def _toy_setup(lr=1e-2, seed=0):
    model = ToyRegressor()
    optimizer = optax.sgd(lr)
    ds = MaterializedDataset(256, seed=seed)
    loader = ShardedLoader(ds, 32)
    state = create_train_state(model, optimizer, next(iter(loader))[0], rng_seed=seed)
    return model, optimizer, loader, state


def test_loss_decreases_serial():
    model, optimizer, loader, state = _toy_setup()
    step = make_train_step(model.apply, optimizer, mse_loss)
    first = last = None
    for epoch in range(20):
        for xs, ys in loader:
            state, loss = step(state, (jnp.asarray(xs), jnp.asarray(ys)))
            if first is None:
                first = float(loss)
            last = float(loss)
    assert last < first * 0.9


def test_gradients_match_finite_differences():
    model, optimizer, loader, state = _toy_setup()
    xs, ys = next(iter(loader))
    xs, ys = jnp.asarray(xs), jnp.asarray(ys)

    def loss_of(params):
        return mse_loss(model.apply({"params": params}, xs), ys)

    grads = jax.grad(loss_of)(state.params)
    flat_grads, _ = jax.tree_util.tree_flatten(grads)
    flat_params, treedef = jax.tree_util.tree_flatten(state.params)
    eps = 1e-3
    # Perturb one scalar of the kernel and compare against the analytic grad.
    kernel_idx = max(range(len(flat_params)), key=lambda i: flat_params[i].size)
    p = flat_params[kernel_idx]
    bumped = p.at[(0,) * p.ndim].add(eps)
    flat_bumped = list(flat_params)
    flat_bumped[kernel_idx] = bumped
    fd = (loss_of(jax.tree_util.tree_unflatten(treedef, flat_bumped)) - loss_of(state.params)) / eps
    analytic = flat_grads[kernel_idx][(0,) * p.ndim]
    np.testing.assert_allclose(float(fd), float(analytic), rtol=1e-2, atol=1e-3)


def test_step_counter_increments():
    model, optimizer, loader, state = _toy_setup()
    step = make_train_step(model.apply, optimizer, mse_loss)
    xs, ys = next(iter(loader))
    state, _ = step(state, (jnp.asarray(xs), jnp.asarray(ys)))
    state, _ = step(state, (jnp.asarray(xs), jnp.asarray(ys)))
    assert int(state.step) == 2


def test_data_parallel_matches_serial():
    """The DDP-parity property the reference only implies: with the same seed
    and the same global batch, the 8-way sharded step produces the same params
    and loss trajectory as the serial step."""
    assert jax.device_count() == 8, "conftest must provide 8 virtual devices"
    model, optimizer, loader, serial_state = _toy_setup()
    # Independent-but-identical state: device_put can alias the source buffer
    # as the device-0 shard, and the serial step donates its input state.
    _, _, _, dp_state0 = _toy_setup()

    serial_step = make_train_step(model.apply, optimizer, mse_loss)
    mesh = make_mesh()
    dp_step = make_train_step(model.apply, optimizer, mse_loss, mesh=mesh)

    dp_state = jax.device_put(dp_state0, replicated_sharding(mesh))

    losses_serial, losses_dp = [], []
    for epoch in range(2):
        loader.set_epoch(epoch)
        for xs, ys in loader:
            serial_state, l1 = serial_step(serial_state, (jnp.asarray(xs), jnp.asarray(ys)))
            dp_state, l2 = dp_step(dp_state, put_global_batch(mesh, (xs, ys)))
            losses_serial.append(float(l1))
            losses_dp.append(float(l2))

    np.testing.assert_allclose(losses_serial, losses_dp, rtol=1e-5, atol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(serial_state.params),
        jax.tree_util.tree_leaves(dp_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_dp_batch_actually_sharded():
    mesh = make_mesh()
    xs = np.zeros((32, 20), np.float32)
    arr = put_global_batch(mesh, xs)
    assert len(arr.sharding.device_set) == 8
    assert arr.addressable_shards[0].data.shape == (4, 20)
