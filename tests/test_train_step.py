"""train_step unit tests: loss decreases, gradients correct, DP == serial.

Mirrors SURVEY.md §4's designed strategy (the reference has no tests at all).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_pytorch_tpu.models.toy import ToyRegressor
from distributed_pytorch_tpu.parallel.mesh import make_mesh
from distributed_pytorch_tpu.parallel.sharding import put_global_batch, replicated_sharding
from distributed_pytorch_tpu.training.losses import mse_loss
from distributed_pytorch_tpu.training.train_step import (
    create_train_state,
    make_train_step,
)
from distributed_pytorch_tpu.utils.data import MaterializedDataset, ShardedLoader


def _toy_setup(lr=1e-2, seed=0):
    model = ToyRegressor()
    optimizer = optax.sgd(lr)
    ds = MaterializedDataset(256, seed=seed)
    loader = ShardedLoader(ds, 32)
    state = create_train_state(model, optimizer, next(iter(loader))[0], rng_seed=seed)
    return model, optimizer, loader, state


def test_loss_decreases_serial():
    model, optimizer, loader, state = _toy_setup()
    step = make_train_step(model.apply, optimizer, mse_loss)
    first = last = None
    for epoch in range(20):
        for xs, ys in loader:
            state, loss = step(state, (jnp.asarray(xs), jnp.asarray(ys)))
            if first is None:
                first = float(loss)
            last = float(loss)
    assert last < first * 0.9


def test_gradients_match_finite_differences():
    model, optimizer, loader, state = _toy_setup()
    xs, ys = next(iter(loader))
    xs, ys = jnp.asarray(xs), jnp.asarray(ys)

    def loss_of(params):
        return mse_loss(model.apply({"params": params}, xs), ys)

    grads = jax.grad(loss_of)(state.params)
    flat_grads, _ = jax.tree_util.tree_flatten(grads)
    flat_params, treedef = jax.tree_util.tree_flatten(state.params)
    eps = 1e-3
    # Perturb one scalar of the kernel and compare against the analytic grad.
    kernel_idx = max(range(len(flat_params)), key=lambda i: flat_params[i].size)
    p = flat_params[kernel_idx]
    bumped = p.at[(0,) * p.ndim].add(eps)
    flat_bumped = list(flat_params)
    flat_bumped[kernel_idx] = bumped
    fd = (loss_of(jax.tree_util.tree_unflatten(treedef, flat_bumped)) - loss_of(state.params)) / eps
    analytic = flat_grads[kernel_idx][(0,) * p.ndim]
    np.testing.assert_allclose(float(fd), float(analytic), rtol=1e-2, atol=1e-3)


def test_step_counter_increments():
    model, optimizer, loader, state = _toy_setup()
    step = make_train_step(model.apply, optimizer, mse_loss)
    xs, ys = next(iter(loader))
    state, _ = step(state, (jnp.asarray(xs), jnp.asarray(ys)))
    state, _ = step(state, (jnp.asarray(xs), jnp.asarray(ys)))
    assert int(state.step) == 2


def test_data_parallel_matches_serial():
    """The DDP-parity property the reference only implies: with the same seed
    and the same global batch, the 8-way sharded step produces the same params
    and loss trajectory as the serial step."""
    assert jax.device_count() == 8, "conftest must provide 8 virtual devices"
    model, optimizer, loader, serial_state = _toy_setup()
    # Independent-but-identical state: device_put can alias the source buffer
    # as the device-0 shard, and the serial step donates its input state.
    _, _, _, dp_state0 = _toy_setup()

    serial_step = make_train_step(model.apply, optimizer, mse_loss)
    mesh = make_mesh()
    dp_step = make_train_step(model.apply, optimizer, mse_loss, mesh=mesh)

    dp_state = jax.device_put(dp_state0, replicated_sharding(mesh))

    losses_serial, losses_dp = [], []
    for epoch in range(2):
        loader.set_epoch(epoch)
        for xs, ys in loader:
            serial_state, l1 = serial_step(serial_state, (jnp.asarray(xs), jnp.asarray(ys)))
            dp_state, l2 = dp_step(dp_state, put_global_batch(mesh, (xs, ys)))
            losses_serial.append(float(l1))
            losses_dp.append(float(l2))

    np.testing.assert_allclose(losses_serial, losses_dp, rtol=1e-5, atol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(serial_state.params),
        jax.tree_util.tree_leaves(dp_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_dp_batch_actually_sharded():
    mesh = make_mesh()
    xs = np.zeros((32, 20), np.float32)
    arr = put_global_batch(mesh, xs)
    assert len(arr.sharding.device_set) == 8
    assert arr.addressable_shards[0].data.shape == (4, 20)


class TestDropout:
    """TrainState.rng arms per-step dropout keys (fold_in(rng, step)):
    deterministic replay across resumes, distinct masks across steps and
    microbatches, inert everywhere the rng is absent."""

    KW = dict(vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)

    def _model_and_batch(self, rate=0.2):
        from distributed_pytorch_tpu.models.transformer import TransformerLM

        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 64, (8, 17)), jnp.int32)
        model = TransformerLM(**self.KW, dropout_rate=rate)
        return model, (tokens[:, :-1], tokens[:, 1:])

    def test_eval_paths_deterministic_without_rng(self):
        model, (inputs, _) = self._model_and_batch()
        params = model.init(jax.random.PRNGKey(0), inputs)["params"]
        a = model.apply({"params": params}, inputs)
        b = model.apply({"params": params}, inputs)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_dropout_changes_training_and_replays_identically(self):
        from distributed_pytorch_tpu.training.losses import (
            softmax_cross_entropy_loss,
        )

        model, batch = self._model_and_batch()
        opt = optax.adam(1e-3)
        step = make_train_step(model.apply, opt, softmax_cross_entropy_loss)

        def run(seed):
            state = create_train_state(
                model, opt, batch[0], dropout_rng=seed
            )
            losses = []
            for _ in range(3):
                state, loss = step(state, batch)
                losses.append(float(loss))
            return losses

        a = run(7)
        b = run(7)
        c = run(8)
        assert a == b  # same base key -> identical mask sequence
        assert a != c  # different key -> different masks
        # Distinct per-step keys: even on a constant batch the per-step
        # losses differ (same mask every step would repeat values).
        assert len(set(np.round(a, 6))) > 1

    def test_rng_none_is_structurally_inert(self):
        from distributed_pytorch_tpu.training.losses import (
            softmax_cross_entropy_loss,
        )

        model, batch = self._model_and_batch(rate=0.0)
        opt = optax.adam(1e-3)
        step = make_train_step(model.apply, opt, softmax_cross_entropy_loss)
        s1 = create_train_state(model, opt, batch[0])
        assert s1.rng is None
        s1, l1 = step(s1, batch)
        s2 = create_train_state(model, opt, batch[0])
        s2, l2 = step(s2, batch)
        assert float(l1) == float(l2)

    def test_grad_accum_uses_distinct_micro_masks(self):
        from distributed_pytorch_tpu.training.losses import (
            softmax_cross_entropy_loss,
        )

        model, batch = self._model_and_batch()
        opt = optax.adam(1e-3)
        step1 = make_train_step(model.apply, opt, softmax_cross_entropy_loss)
        step2 = make_train_step(
            model.apply, opt, softmax_cross_entropy_loss, grad_accum=2
        )
        sa = create_train_state(model, opt, batch[0], dropout_rng=7)
        sb = create_train_state(model, opt, batch[0], dropout_rng=7)
        _, la = step1(sa, batch)
        _, lb = step2(sb, batch)
        # Both run; different mask granularity makes them differ (would be
        # equal at rate=0 — the mean-of-means contract, pinned elsewhere).
        assert np.isfinite(float(la)) and np.isfinite(float(lb))
        assert float(la) != float(lb)

    def test_snapshot_resume_replays_masks(self, tmp_path):
        from distributed_pytorch_tpu.checkpoint import (
            load_snapshot,
            save_snapshot,
        )
        from distributed_pytorch_tpu.training.losses import (
            softmax_cross_entropy_loss,
        )

        model, batch = self._model_and_batch()
        opt = optax.adam(1e-3)
        step = make_train_step(model.apply, opt, softmax_cross_entropy_loss)
        state = create_train_state(model, opt, batch[0], dropout_rng=7)
        state, _ = step(state, batch)
        path = str(tmp_path / "s.npz")
        save_snapshot(path, state, epochs_run=1)
        _, cont = step(state, batch)

        template = create_train_state(model, opt, batch[0], dropout_rng=0)
        restored, _ = load_snapshot(path, template)
        _, resumed = step(restored, batch)
        # fold_in(rng, step) with both rng and step restored -> the resumed
        # process draws the SAME mask as the uninterrupted one.
        np.testing.assert_allclose(float(cont), float(resumed), rtol=1e-6)


def test_dropout_composes_with_sharded_state_specs():
    """TrainState.rng must survive the partitioning spec builders (a
    missing field would crash device_put with a tree mismatch)."""
    import optax as _optax

    from distributed_pytorch_tpu.models.transformer import TransformerLM
    from distributed_pytorch_tpu.parallel.mesh import make_mesh
    from distributed_pytorch_tpu.parallel.partitioning import (
        TRANSFORMER_TP_RULES,
        make_param_specs,
        make_state_shardings,
        make_zero1_shardings,
        shard_train_state,
    )

    mesh = make_mesh({"data": 4, "tensor": 2})
    model = TransformerLM(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        dropout_rate=0.1,
    )
    tokens = jnp.zeros((4, 8), jnp.int32)
    state = create_train_state(
        model, _optax.adam(1e-3), tokens, dropout_rng=3
    )
    specs = make_param_specs(state.params, TRANSFORMER_TP_RULES, mesh=mesh)
    shardings = make_state_shardings(mesh, state, specs)
    sharded = shard_train_state(state, shardings)
    assert sharded.rng is not None
    z = make_zero1_shardings(make_mesh({"data": 8}), state)
    sharded_z = shard_train_state(state, z)
    assert sharded_z.rng is not None


def test_smoothed_loss_per_sample_handles_sequence_logits():
    from distributed_pytorch_tpu.training.losses import (
        PER_SAMPLE_TWINS,
        smoothed_cross_entropy_loss,
    )

    loss_fn = smoothed_cross_entropy_loss(0.1)
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((3, 7, 5)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, 5, (3, 7)), jnp.int32)
    per = PER_SAMPLE_TWINS[loss_fn](logits, targets)
    assert per.shape == (3,)  # [batch], token dims reduced
    np.testing.assert_allclose(
        float(jnp.mean(per)), float(loss_fn(logits, targets)), rtol=1e-6
    )


def test_numpy_integer_seed_becomes_key():
    import optax as _optax

    from distributed_pytorch_tpu.models.mlp import MLP

    xs = jnp.zeros((4, 20), jnp.float32)
    state = create_train_state(
        MLP(hidden=(8,), features=2), _optax.sgd(1e-2), xs,
        dropout_rng=np.int64(7),
    )
    # Must be a usable key, not a raw numpy scalar.
    jax.random.fold_in(state.rng, 0)
