"""Routed-fleet tests: affinity routing, health-checked failover, hedging
dedup, drain handoff, and the fleet-level chaos drill.

The headline drill mirrors the single-engine elastic story one level up:
three replicas under seeded Poisson load, a ``kill_replica`` chaos fault
SIGKILLs (in-process: abandons) the replica that affinity routing loaded
mid-decode, and EVERY request — in flight on the dead replica, queued, or
elsewhere — must finish with greedy tokens identical to an uninterrupted
single-engine reference, with zero referenced pages left on any survivor.
Determinism does the heavy lifting: token i of a request is drawn from
``fold_in(key(seed), i)`` regardless of engine, slot, or batch, so the
router's shadow snapshots re-admitted through ``restore_engine`` regenerate
byte-identical tails.

All on CPU (conftest pins JAX_PLATFORMS=cpu).
"""

import json
import os
import random

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu import chaos
from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.serving import (
    FleetRouter,
    InferenceEngine,
    QueueFull,
    SamplingParams,
    drain_engine,
    prefix_affinity_key,
    restore_engine,
)
from distributed_pytorch_tpu.serving.fleet import (
    ID_STRIDE,
    AutoscalePolicy,
    _rendezvous,
)
from distributed_pytorch_tpu.serving.kv_cache import (
    PagedBlockAllocator,
    PrefixCache,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _fresh_chaos_plan():
    chaos._reset()
    yield
    os.environ.pop(chaos.ENV_VAR, None)
    chaos._reset()


def tiny_lm():
    return TransformerLM(
        vocab_size=48, d_model=16, n_layers=1, n_heads=2, d_ff=32,
        dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def target_and_params():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


ENGINE_KW = dict(
    max_slots=2, max_seq_len=32, page_size=4, token_budget=16,
    max_prefill_chunk=8, debug=True,
)
MAX_NEW = 6
PAGE = ENGINE_KW["page_size"]

# One full page shared by the affinity group (page-aligned => routable).
PREFIX = [5, 7, 11, 2]
AFFINITY_PROMPTS = [PREFIX + [t, t + 1] for t in (1, 9, 17, 25, 33)]
OTHER_PROMPTS = [[2, 2, 3, 17, 40], [6, 1, 9], [40, 41], [3, 3, 3, 3, 8]]
DRILL_PROMPTS = AFFINITY_PROMPTS + OTHER_PROMPTS


def make_engine(model, params, **kw):
    opts = dict(ENGINE_KW)
    opts.update(kw)
    return InferenceEngine(model, params, **opts)


def make_fleet(model, params, n=3, *, engine_kw=None, **router_kw):
    engines = [
        make_engine(model, params, **(engine_kw or {})) for _ in range(n)
    ]
    return FleetRouter(engines, **router_kw)


def params_for(i):
    return SamplingParams(max_new_tokens=MAX_NEW)


@pytest.fixture(scope="module")
def ref_outputs(target_and_params):
    """Uninterrupted single-engine greedy reference, keyed by prompt
    index. Output streams are batch/slot/engine-invariant, so this one
    reference serves every fleet scenario."""
    model, params = target_and_params
    eng = make_engine(model, params)
    ids = [
        eng.submit(p, params_for(i)) for i, p in enumerate(DRILL_PROMPTS)
    ]
    eng.run()
    out = {i: eng.poll(rid).generated for i, rid in enumerate(ids)}
    eng.close()
    return out


def assert_parity(router, fids_by_prompt_idx, ref_outputs):
    for idx, fid in fids_by_prompt_idx.items():
        st = router.poll(fid)
        assert st.finished, f"prompt {idx} (fid {fid}) never finished"
        assert st.generated == ref_outputs[idx], (
            f"prompt {idx}: fleet produced {st.generated}, "
            f"reference {ref_outputs[idx]}"
        )


def arm(plan):
    os.environ[chaos.ENV_VAR] = json.dumps(plan)
    chaos._reset()


# ----------------------------------------------------------------- routing


def test_affinity_key_matches_trie_chain():
    """The router's key and the trie's content address are THE SAME hash:
    what the router computes from raw tokens is what any engine's
    PrefixCache will call the cached page — that identity is the whole
    basis of affinity routing."""
    alloc = PagedBlockAllocator(8)
    cache = PrefixCache(alloc, PAGE)
    tokens = PREFIX + [1, 2, 3, 4, 9]
    # Register the first two full pages in the trie, then compare chains.
    p1, p2 = alloc.allocate(2)
    node, _ = cache.register_full(cache.ROOT, tuple(tokens[:PAGE]), p1)
    cache.register_full(node, tuple(tokens[PAGE : 2 * PAGE]), p2)
    alloc.unref(p1)
    alloc.unref(p2)
    chain = cache.key_chain(tokens)
    assert len(chain) == 2
    assert prefix_affinity_key(tokens, PAGE, pages=1) == chain[0]
    assert prefix_affinity_key(tokens, PAGE, pages=2) == chain[1]
    # Sub-page prompts have nothing page-aligned to share.
    assert prefix_affinity_key(PREFIX[:3], PAGE) is None


def test_affinity_routing_colocates_shared_prefixes(target_and_params):
    model, params = target_and_params
    router = make_fleet(model, params, n=3)
    try:
        fids = [router.submit(p, params_for(0)) for p in AFFINITY_PROMPTS]
        owners = {router._shadows[f].replica for f in fids}
        assert len(owners) == 1, (
            f"shared-prefix requests split across {owners}"
        )
        # And the owner is the rendezvous choice, not an accident of load.
        key = prefix_affinity_key(AFFINITY_PROMPTS[0], PAGE)
        assert owners == {_rendezvous(key, ["r0", "r1", "r2"])}
        assert router.registry.read_counter("routed_affinity_total") == len(
            fids
        )
        router.run()
    finally:
        router.close()


def test_least_loaded_fallback_spreads_short_prompts(target_and_params):
    model, params = target_and_params
    router = make_fleet(model, params, n=3)
    try:
        # Sub-page prompts carry no affinity key: each goes to the least
        # loaded replica, so six submits spread 2/2/2.
        fids = [
            router.submit([7 + i, 3], params_for(0)) for i in range(6)
        ]
        owners = [router._shadows[f].replica for f in fids]
        assert sorted(owners) == ["r0", "r0", "r1", "r1", "r2", "r2"]
        assert (
            router.registry.read_counter("routed_least_loaded_total") == 6
        )
        router.run()
    finally:
        router.close()


def test_replica_ids_are_namespaced(target_and_params):
    """Per-replica id namespacing is the collision guard that lets one
    survivor adopt several peers' requests: r0 mints from 0, r1 from
    ID_STRIDE."""
    model, params = target_and_params
    router = make_fleet(model, params, n=2)
    try:
        f0 = router.submit([9, 1], params_for(0))
        f1 = router.submit([9, 2], params_for(0))
        ids = sorted(
            router._shadows[f].req_id for f in (f0, f1)
        )
        assert ids[0] < ID_STRIDE <= ids[1]
        router.run()
    finally:
        router.close()


# --------------------------------------------------------- the chaos drill


def test_fleet_kill_drill_token_parity(target_and_params, ref_outputs):
    """The acceptance drill: SIGKILL (in-process) one of three replicas
    mid-decode under seeded Poisson load; every request completes on the
    survivors with greedy tokens identical to the single-engine reference
    and zero referenced pages remain anywhere."""
    model, params = target_and_params
    # Kill the replica the affinity group routes to, so the fault lands on
    # a replica that is provably decoding when it dies.
    key = prefix_affinity_key(AFFINITY_PROMPTS[0], PAGE)
    victim = _rendezvous(key, ["r0", "r1", "r2"])
    victim_idx = int(victim[1:])
    arm({
        "seed": 1234,
        "faults": [
            {"kind": "kill_replica", "replica": victim_idx, "at_step": 3}
        ],
    })
    router = make_fleet(model, params, n=3, probe_every=2)
    rng = random.Random(1234)
    # Seeded Poisson-ish arrivals: every prompt gets a submit round drawn
    # from a geometric gap process; the affinity group goes first so the
    # victim holds their decode when round 3 kills it.
    schedule = {}
    rnd = 0
    for idx in range(len(DRILL_PROMPTS)):
        schedule.setdefault(rnd, []).append(idx)
        while rng.random() < 0.5:
            rnd += 1
    fids = {}
    try:
        rounds = 0
        while True:
            for idx in schedule.pop(rounds, []):
                fids[idx] = router.submit(
                    DRILL_PROMPTS[idx], params_for(idx)
                )
            done = not schedule and all(
                s.finished for s in router._shadows.values()
            )
            if done and len(fids) == len(DRILL_PROMPTS):
                break
            router.step()
            rounds += 1
            assert rounds < 500, "drill did not converge"

        dead = [r for r in router.replicas() if r.state == "dead"]
        assert [r.name for r in dead] == [victim]
        assert dead[0].dead_reason == "kill_replica"
        assert (
            router.registry.read_counter("requests_failed_over_total") >= 1
        )
        # Detection latency was recorded (kill -> declaration, same pump
        # loop here, so small but present).
        assert (
            router.registry.read_gauge("dead_replica_detection_seconds")
            >= 0.0
        )
        assert router._detect_hist.count == 1
        assert_parity(router, fids, ref_outputs)
        # Zero leaked pages on every survivor.
        for rep in router.replicas():
            if rep.state == "dead":
                continue
            assert (
                rep.engine.registry.read_gauge("pages_referenced") == 0
            ), f"{rep.name} leaked referenced pages"
    finally:
        router.close()  # closes survivors; close() leak-checks them


def test_fleet_kill_drill_one_trace_id_spans_failover(
    target_and_params, ref_outputs
):
    """Distributed-tracing face of the kill drill: a request that fails
    over keeps ONE trace_id across the door, the router, the original
    replica, and the survivor — and its merged waterfall attributes a
    nonzero ``failover_gap`` while still summing to the e2e latency."""
    from distributed_pytorch_tpu.obs import (
        TraceSampler,
        Tracer,
        merge_traces,
        request_waterfall,
        trace_ids,
    )
    from distributed_pytorch_tpu.serving import FrontDoor, TenantConfig

    model, params = target_and_params
    engines = [
        make_engine(model, params, tracer=Tracer()) for _ in range(3)
    ]
    router = FleetRouter(engines, tracer=Tracer(), probe_every=2)
    door = FrontDoor(
        router,
        tenants={"anon": TenantConfig()},
        tracer=Tracer(),
        sampler=TraceSampler(head_rate=1.0, max_kept=64),
    )
    try:
        streams = [
            door.open_stream(p, params=params_for(i))
            for i, p in enumerate(AFFINITY_PROMPTS)
        ]
        # Admit + route first, then aim the kill at whichever replica the
        # affinity group actually landed on — the fault must hit a
        # replica that is provably decoding these requests.
        door.pump()
        victim_name = router._shadows[streams[0].req_id].replica
        victim_idx = next(
            i for i, rep in enumerate(router.replicas())
            if rep.name == victim_name
        )
        arm({
            "seed": 1234,
            "faults": [
                {"kind": "kill_replica", "replica": victim_idx,
                 "at_step": 2}
            ],
        })
        door.drive()
        outs = [s.drain() for s in streams]

        dead = [r.name for r in router.replicas() if r.state == "dead"]
        assert dead == [victim_name]
        for i, out in enumerate(outs):
            assert out == ref_outputs[i], f"stream {i} diverged"
        moved = [
            s for s in streams
            if router._shadows[s.req_id].failovers > 0
        ]
        assert moved, "kill landed but no stream failed over"

        merged = merge_traces(*door.trace_documents())
        assert len(trace_ids(merged)) == len(streams)
        victim = moved[0]
        # ONE trace_id opens spans on door, router, AND both engine
        # incarnations — four distinct process lanes minimum.
        opened_pids = {
            e["pid"]
            for e in merged["traceEvents"]
            if e.get("ph") == "b"
            and e.get("args", {}).get("trace_id") == victim.trace_id
        }
        assert len(opened_pids) >= 4, (
            f"victim {victim.trace_id} only on lanes {sorted(opened_pids)}"
        )
        wf = request_waterfall(merged, victim.trace_id)
        assert wf["components"]["failover_gap"] > 0
        total = sum(wf["components"].values())
        assert abs(total - wf["e2e_s"]) <= 0.05 * wf["e2e_s"]
    finally:
        router.close()


def test_partition_death_and_blip(target_and_params, ref_outputs):
    """A partitioned replica that stays unreachable past the probe
    threshold is declared dead and its work fails over; one that heals
    within the window is a blip — nothing moves, nothing diverges."""
    model, params = target_and_params
    # Death: permanent partition, threshold 2, probing every round.
    router = make_fleet(
        model, params, n=2, probe_every=1, probe_fail_threshold=2
    )
    fids = {}
    try:
        for idx, p in enumerate(DRILL_PROMPTS[:4]):
            fids[idx] = router.submit(p, params_for(idx))
        router.step()
        victim = router._shadows[fids[0]].replica
        router._apply_fault(
            chaos.Fault(
                kind="partition_replica",
                replica=int(victim[1:]),
                duration=0.0,  # 0 = until the run ends
            )
        )
        router.run()
        assert router._by_name[victim].state == "dead"
        assert router._by_name[victim].dead_reason == "probe_failures"
        assert_parity(router, fids, ref_outputs)
    finally:
        router.close()

    # Blip: partition shorter than the detection window heals in place.
    router = make_fleet(
        model, params, n=2, probe_every=1, probe_fail_threshold=50
    )
    fids = {}
    try:
        for idx, p in enumerate(DRILL_PROMPTS[:4]):
            fids[idx] = router.submit(p, params_for(idx))
        router.step()
        router._apply_fault(
            chaos.Fault(
                kind="partition_replica", replica=0, duration=0.05
            )
        )
        router.run()
        assert all(r.state == "live" for r in router.replicas())
        assert (
            router.registry.read_counter("requests_failed_over_total") == 0
        )
        assert_parity(router, fids, ref_outputs)
    finally:
        router.close()


# ----------------------------------------------------- draining (satellite)


def test_draining_replica_streams_to_completion(
    target_and_params, ref_outputs
):
    """A replica answering *draining* (the /healthz-503 verdict) leaves
    the admission rotation but is NOT evicted: its in-flight requests
    keep streaming to completion on it while new traffic lands
    elsewhere."""
    model, params = target_and_params
    router = make_fleet(model, params, n=2, probe_every=1)
    fids = {}
    try:
        for idx in range(4):
            fids[idx] = router.submit(
                DRILL_PROMPTS[idx], params_for(idx)
            )
        router.step()
        drainer = router._shadows[fids[0]].replica
        other = "r1" if drainer == "r0" else "r0"
        in_flight_on_drainer = [
            f
            for f in fids.values()
            if router._shadows[f].replica == drainer
        ]
        assert in_flight_on_drainer
        # The external notice: admission closes, health() says draining.
        router._by_name[drainer].engine.stop_admission()
        router.step()  # probe sweep picks the verdict up
        assert router._by_name[drainer].state == "draining"
        # New traffic routes around it — including affinity traffic whose
        # rendezvous choice it may have been.
        for idx in range(4, 8):
            fids[idx] = router.submit(
                DRILL_PROMPTS[idx], params_for(idx)
            )
            assert router._shadows[fids[idx]].replica == other
        router.run()
        # Never evicted, never died: the drainer finished its own work.
        assert router._by_name[drainer].state == "draining"
        for f in in_flight_on_drainer:
            assert router._shadows[f].replica == drainer
        assert (
            router.registry.read_counter("requests_failed_over_total") == 0
        )
        assert_parity(router, fids, ref_outputs)
    finally:
        router.close()


def test_submit_discovers_draining_before_probe(target_and_params):
    """EngineDraining from submit is 'retry elsewhere, now': even with
    probes effectively off, the router reroutes on the spot and flips the
    route-table state."""
    model, params = target_and_params
    router = make_fleet(model, params, n=2, probe_every=10_000)
    try:
        router._by_name["r0"].engine.stop_admission()
        fid = router.submit([9, 4], params_for(0))
        fid2 = router.submit(AFFINITY_PROMPTS[0], params_for(0))
        assert router._shadows[fid].replica == "r1"
        assert router._shadows[fid2].replica == "r1"
        assert router._by_name["r0"].state == "draining"
        router.run()
    finally:
        router.close()


class _DictStore:
    """Minimal in-process stand-in for KVStoreClient's get/set/delete."""

    def __init__(self):
        self.data = {}

    def set(self, key, value):
        self.data[key] = value

    def get(self, key):
        return self.data.get(key)

    def delete(self, key):
        self.data.pop(key, None)


def test_drain_replica_handoff_via_store(target_and_params, ref_outputs):
    """Router-initiated SIGTERM handoff: drain one replica, publish its
    snapshot through the elastic store, adopt on the survivor — zero
    token divergence and the drained engine closes leak-checked."""
    model, params = target_and_params
    router = make_fleet(model, params, n=2)
    store = _DictStore()
    fids = {}
    try:
        for idx in range(6):
            fids[idx] = router.submit(
                DRILL_PROMPTS[idx], params_for(idx)
            )
        router.step()
        victim = router._shadows[fids[0]].replica
        moved = router.drain_replica(victim, store=store)
        assert moved >= 1
        assert router._by_name[victim].state == "removed"
        assert not store.data, "handoff key should be adopt-once deleted"
        router.run()
        assert_parity(router, fids, ref_outputs)
        assert (
            router.registry.read_counter("drain_handoffs_total") == 1
        )
    finally:
        router.close()


# ----------------------------------------------------------------- hedging


def test_hedging_dedup_single_emission(target_and_params, ref_outputs):
    """With an aggressive hedge deadline every request gets a twin on the
    other replica; determinism makes the copies identical, the first to
    finish wins, and the dedup rule guarantees exactly one emission per
    fleet id."""
    model, params = target_and_params
    router = make_fleet(model, params, n=2, hedge_after_s=0.0)
    fids = {}
    emitted = []
    try:
        for idx in range(4):
            fids[idx] = router.submit(
                DRILL_PROMPTS[idx], params_for(idx)
            )
        rounds = 0
        while not all(s.finished for s in router._shadows.values()):
            emitted.extend(router.step())
            rounds += 1
            assert rounds < 200
        assert router.registry.read_counter("hedges_total") >= 1
        # Exactly one emission per fleet id, ever.
        assert sorted(emitted) == sorted(fids.values())
        assert_parity(router, fids, ref_outputs)
    finally:
        router.close()


def test_slow_replica_fault_triggers_hedge(target_and_params, ref_outputs):
    """The chaos straggler: slow_replica injects per-step delay on one
    replica, the hedge fires against the wall-clock deadline, and the
    fast twin wins without double emission."""
    model, params = target_and_params
    arm({
        "seed": 5,
        "faults": [
            {"kind": "slow_replica", "replica": 0, "duration": 0.02,
             "at_step": 1}
        ],
    })
    router = make_fleet(model, params, n=2, hedge_after_s=0.01)
    fids = {}
    emitted = []
    try:
        # Pin the first request to r0 (both empty, tie broken by index).
        fids[0] = router.submit(DRILL_PROMPTS[0], params_for(0))
        rounds = 0
        while not all(s.finished for s in router._shadows.values()):
            emitted.extend(router.step())
            rounds += 1
            assert rounds < 200
        assert router._by_name["r0"].slow_delay_s == 0.02
        assert router.registry.read_counter("hedges_total") >= 1
        assert sorted(emitted) == sorted(fids.values())
        assert_parity(router, fids, ref_outputs)
    finally:
        router.close()


# --------------------------------------------------------------- admission


def test_queue_full_retries_across_replicas(target_and_params):
    """QueueFull means 'retry later': bounded backoff, then the next-best
    replica. Affinity traffic overflowing its home replica spills; a
    fleet-wide full queue surfaces the error to the caller."""
    model, params = target_and_params
    router = make_fleet(
        model, params, n=2, engine_kw=dict(max_queue=1),
        retry_backoff_s=0.001,
    )
    try:
        a = router.submit(AFFINITY_PROMPTS[0], params_for(0))
        b = router.submit(AFFINITY_PROMPTS[1], params_for(1))
        owners = {
            router._shadows[f].replica for f in (a, b)
        }
        assert len(owners) == 2, "overflow should spill to the peer"
        assert (
            router.registry.read_counter("submit_retries_total") >= 1
        )
        with pytest.raises(QueueFull):
            router.submit(AFFINITY_PROMPTS[2], params_for(2))
        assert (
            router.registry.read_counter("submit_rejected_total") == 1
        )
        router.run()
    finally:
        router.close()


# ------------------------------------------------------------- autoscaling


class _FiringSLO:
    def state(self):
        return {"ttft_p95": {"firing": True}}


class _IdleGoodput:
    productive_s = 1.0
    wasted = {"budget_idle": 9.0}

    def wasted_total_s(self):
        return 9.0

    def note_drain(self):
        pass


def test_autoscale_out_on_slo_and_in_on_idle(target_and_params):
    """The closed SRE loop: a firing burn-rate alert grows the fleet from
    the factory; fleet-wide budget-idle waste shrinks it through a clean
    drain."""
    model, params = target_and_params
    policy = AutoscalePolicy(min_replicas=1, max_replicas=3)
    router = make_fleet(
        model, params, n=2, autoscale=policy,
        engine_factory=lambda: make_engine(model, params),
    )
    try:
        router._by_name["r0"].engine.slo = _FiringSLO()
        action = router.maybe_autoscale()
        assert action == ("out", "r2")
        assert len(router._eligible()) == 3
        assert router.registry.read_counter("scale_outs_total") == 1
        # New replica minted into its own id namespace.
        assert router._by_name["r2"].engine._next_id == 2 * ID_STRIDE

        router._by_name["r0"].engine.slo = None
        for rep in router.replicas():
            rep.engine.goodput = _IdleGoodput()
        action = router.maybe_autoscale()
        assert action is not None and action[0] == "in"
        assert router.registry.read_counter("scale_ins_total") == 1
        assert len(router._eligible()) == 2
    finally:
        router.close()


# ----------------------------------------------------------- observability


def test_fleet_snapshot_merges_router_and_replicas(target_and_params):
    model, params = target_and_params
    router = make_fleet(model, params, n=2)
    try:
        fid = router.submit([4, 4, 4], params_for(0))
        router.run()
        assert router.poll(fid).finished
        snap = router.fleet_snapshot()
        assert snap["counters"]["fleet_submitted_total"] == 1
        # Replica registries merged in: serving-side metrics present and
        # summed across both replicas.
        assert any(
            name.startswith("serving_") for name in snap["counters"]
        )
        assert snap["gauges"]["fleet_replicas_live"] == 2
        assert router.registry.read_gauge("replica_r0_health") == 1.0
        # Health gauge tracks the route table.
        router._apply_fault(
            chaos.Fault(kind="kill_replica", replica=1)
        )
        router.step()
        assert router.registry.read_gauge("replica_r1_health") == 0.0
        assert router.describe()["replicas"][1]["state"] == "dead"
    finally:
        router.close()


def test_fingerprint_mismatch_refused(target_and_params):
    model, params = target_and_params
    e1 = make_engine(model, params)
    e2 = make_engine(model, params, page_size=8)
    try:
        with pytest.raises(ValueError, match="fingerprint"):
            FleetRouter([e1, e2])
    finally:
        e1.close()
        e2.close()


# ----------------------------------------------- id collision (satellite 2)


def test_overlapping_snapshot_ids_need_rebase(
    target_and_params, ref_outputs
):
    """Failover re-admission of two replicas' snapshots into one survivor
    must not collide request ids: without namespacing the duplicate id is
    refused loudly, and ``rebase_ids=True`` mints fresh ids with no token
    divergence."""
    model, params = target_and_params
    a = make_engine(model, params)
    b = make_engine(model, params)
    for idx in range(2):
        a.submit(DRILL_PROMPTS[idx], params_for(idx))
    for idx in range(2, 4):
        b.submit(DRILL_PROMPTS[idx], params_for(idx))
    snap_a, snap_b = drain_engine(a), drain_engine(b)
    # Both engines minted ids from 0: the id spaces overlap exactly.
    assert {r.req_id for r in snap_a.requests} == {
        r.req_id for r in snap_b.requests
    }
    survivor = make_engine(model, params, max_queue=16)
    ids_a = restore_engine(survivor, snap_a)
    with pytest.raises(ValueError, match="rebase_ids"):
        restore_engine(survivor, snap_b)
    ids_b = restore_engine(survivor, snap_b, rebase_ids=True)
    assert not set(ids_a) & set(ids_b)
    survivor.run()
    for idx, rid in enumerate(ids_a + ids_b):
        assert survivor.poll(rid).generated == ref_outputs[idx]
    survivor.close()
    a.close()
    b.close()
