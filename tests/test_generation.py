"""KV-cache generation tests: the decode path must agree exactly with the
full-context forward pass."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from distributed_pytorch_tpu.generation import generate
from distributed_pytorch_tpu.models import TransformerLM


def tiny_lm(**kw):
    return TransformerLM(
        vocab_size=48, d_model=16, n_layers=2, n_heads=2, d_ff=32, **kw
    )


def make_params(model, batch=2, seq=12, seed=0):
    tokens = np.random.default_rng(seed).integers(0, 48, (batch, seq), np.int32)
    return model.init(jax.random.PRNGKey(1), jnp.asarray(tokens))["params"], tokens


@pytest.mark.slow
def test_decode_logits_match_full_forward():
    """Feeding tokens one at a time through the KV cache must reproduce the
    full-context causal logits at every position."""
    model = tiny_lm()
    params, tokens = make_params(model)
    full_logits = model.apply({"params": params}, jnp.asarray(tokens))

    decode_model = model.clone(decode=True)
    variables = decode_model.init(
        jax.random.PRNGKey(0), jnp.zeros_like(jnp.asarray(tokens))
    )
    cache = variables["cache"]
    step_logits = []
    for t in range(tokens.shape[1]):
        logits, updated = decode_model.apply(
            {"params": params, "cache": cache},
            jnp.asarray(tokens[:, t : t + 1]),
            mutable=["cache"],
        )
        cache = updated["cache"]
        step_logits.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(
        np.stack(step_logits, axis=1), np.asarray(full_logits), atol=2e-4
    )


def test_greedy_generation_is_deterministic_and_preserves_prompt():
    model = tiny_lm()
    params, tokens = make_params(model, batch=3, seq=6)
    out1 = np.asarray(generate(model, params, jnp.asarray(tokens), 8))
    out2 = np.asarray(generate(model, params, jnp.asarray(tokens), 8))
    np.testing.assert_array_equal(out1, out2)
    np.testing.assert_array_equal(out1[:, :6], tokens)
    assert out1.shape == (3, 14)


@pytest.mark.slow
def test_greedy_matches_incremental_full_forward():
    """Greedy generate == repeatedly running the full model and taking argmax
    of the last position (the no-cache oracle)."""
    model = tiny_lm()
    params, tokens = make_params(model, batch=2, seq=5)
    generated = np.asarray(generate(model, params, jnp.asarray(tokens), 6))

    oracle = tokens.copy()
    for _ in range(6):
        logits = model.apply({"params": params}, jnp.asarray(oracle))
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1), np.int32)
        oracle = np.concatenate([oracle, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(generated, oracle)


def test_ragged_prompts():
    model = tiny_lm()
    params, tokens = make_params(model, batch=2, seq=6)
    lengths = jnp.asarray([6, 3], jnp.int32)
    out = np.asarray(
        generate(
            model, params, jnp.asarray(tokens), 4, prompt_lengths=lengths
        )
    )
    # Row 0's full prompt survives; row 1's prompt survives only to length 3
    # (the rest is generated).
    np.testing.assert_array_equal(out[0, :6], tokens[0])
    np.testing.assert_array_equal(out[1, :3], tokens[1, :3])


def test_sampling_with_temperature_and_topk():
    model = tiny_lm()
    params, tokens = make_params(model, batch=2, seq=4)
    out = np.asarray(
        generate(
            model, params, jnp.asarray(tokens), 5,
            temperature=1.0, top_k=5, rng=jax.random.PRNGKey(7),
        )
    )
    assert out.shape == (2, 9)
    assert (out >= 0).all() and (out < 48).all()


# ------------------------------------------------------- sharded generation


class TestShardedGeneration:
    def test_no_donation_warning(self):
        """The KV cache is updated in place inside the decode loop; the old
        useless donation produced 'Some donated buffers were not usable'
        every call — assert it is gone for good."""
        import warnings

        model = tiny_lm()
        params, tokens = make_params(model)
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # any jax buffer warning -> failure
            out = generate(model, params, jnp.asarray(tokens[:, :4]), 5)
        assert out.shape == (2, 9)

    @pytest.mark.slow
    def test_mesh_parity_with_single_device(self):
        """Greedy decode on an 8-device data mesh must produce token-for-token
        the same output as the single-device path."""
        from distributed_pytorch_tpu.parallel.mesh import make_mesh

        model = tiny_lm()
        params, _ = make_params(model, batch=8, seq=6)
        rng = np.random.default_rng(5)
        prompt = jnp.asarray(rng.integers(0, 48, (8, 6)), jnp.int32)

        single = generate(model, params, prompt, 7)
        mesh = make_mesh({"data": 8})
        sharded = generate(model, params, prompt, 7, mesh=mesh)
        # Output is batch-sharded; gather for comparison.
        np.testing.assert_array_equal(np.asarray(sharded), np.asarray(single))
        assert sharded.sharding.spec == jax.sharding.PartitionSpec("data")

    @pytest.mark.slow
    def test_mesh_parity_with_tensor_parallel_params(self):
        """data x tensor mesh with megatron-sharded params: same tokens."""
        from jax.sharding import NamedSharding
        from distributed_pytorch_tpu.parallel.mesh import make_mesh
        from distributed_pytorch_tpu.parallel.partitioning import (
            TRANSFORMER_TP_RULES,
            make_param_specs,
        )

        model = tiny_lm()
        params, _ = make_params(model, batch=4, seq=5)
        rng = np.random.default_rng(9)
        prompt = jnp.asarray(rng.integers(0, 48, (4, 5)), jnp.int32)

        single = generate(model, params, prompt, 6)
        mesh = make_mesh({"data": 4, "tensor": 2})
        specs = make_param_specs(params, TRANSFORMER_TP_RULES, mesh=mesh)
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), specs
        )
        sharded = generate(
            model, params, prompt, 6, mesh=mesh, param_shardings=shardings
        )
        np.testing.assert_array_equal(np.asarray(sharded), np.asarray(single))

    def test_ragged_prompts_on_mesh(self):
        """prompt_lengths (ragged rows) compose with the sharded path."""
        from distributed_pytorch_tpu.parallel.mesh import make_mesh

        model = tiny_lm()
        params, _ = make_params(model, batch=8, seq=6)
        rng = np.random.default_rng(13)
        prompt = jnp.asarray(rng.integers(0, 48, (8, 6)), jnp.int32)
        lengths = jnp.asarray(rng.integers(2, 7, (8,)), jnp.int32)

        single = generate(model, params, prompt, 4, prompt_lengths=lengths)
        mesh = make_mesh({"data": 8})
        sharded = generate(
            model, params, prompt, 4, prompt_lengths=lengths, mesh=mesh
        )
        np.testing.assert_array_equal(np.asarray(sharded), np.asarray(single))


@pytest.mark.slow
def test_parallel_prefill_matches_serial_prompt_walk():
    """The chunked prefill (one batched forward over the common prompt
    prefix) must produce bit-identical greedy output to the all-serial loop
    (prefill_len=1), uniform and ragged."""
    from distributed_pytorch_tpu.generation import _compiled_run

    model = tiny_lm()
    params, tokens = make_params(model, batch=4, seq=10)
    decode_model = model.clone(decode=True)
    prompt = jnp.asarray(tokens[:, :10])
    total_len = 10 + 6

    def run_with(prefill_len, lengths):
        abstract = jax.eval_shape(
            decode_model.init,
            jax.random.PRNGKey(0),
            jnp.zeros((4, total_len), jnp.int32),
        )["cache"]
        cache = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), abstract
        )
        tokens0 = jnp.concatenate(
            [prompt, jnp.zeros((4, 6), jnp.int32)], axis=1
        )
        # Keyword, not positional: top_p sits between top_k and prefill_len
        # in the signature, and a silently-defaulted prefill_len=1 would
        # turn this test into a no-op (it pins the CHUNKED prefill path).
        run = _compiled_run(
            decode_model, total_len, 0.0, 0, prefill_len=prefill_len
        )
        return np.asarray(
            run(params, tokens0, cache, lengths, jax.random.PRNGKey(0))
        )

    uniform = jnp.full((4,), 10, jnp.int32)
    np.testing.assert_array_equal(
        run_with(1, uniform), run_with(10, uniform)
    )
    ragged = jnp.asarray([3, 10, 7, 5], jnp.int32)
    np.testing.assert_array_equal(
        run_with(1, ragged), run_with(3, ragged)  # prefill = min length
    )


def test_gqa_tensor_parallel_decode_parity():
    """GQA decode composes with megatron TP: the kv-head kernels shard over
    the tensor axis (needs n_kv_heads % tp == 0) and mesh greedy decode
    matches the single-device GQA output token for token."""
    import jax.tree_util as jtu
    from jax.sharding import NamedSharding

    from distributed_pytorch_tpu.parallel.mesh import make_mesh

    from distributed_pytorch_tpu.parallel.partitioning import (
        TRANSFORMER_TP_RULES,
        make_param_specs,
    )

    model = TransformerLM(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        n_kv_heads=2,
    )
    rng = np.random.default_rng(5)
    prompt = jnp.asarray(rng.integers(1, 64, (4, 6)), jnp.int32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 6), jnp.int32)
    )["params"]
    single = generate(model, params, prompt, 5)

    mesh = make_mesh({"data": 4, "tensor": 2})
    specs = make_param_specs(params, TRANSFORMER_TP_RULES, mesh=mesh)
    shardings = jtu.tree_map(lambda s: NamedSharding(mesh, s), specs)
    sharded = generate(
        model, params, prompt, 5, mesh=mesh, param_shardings=shardings
    )
    np.testing.assert_array_equal(np.asarray(single), np.asarray(sharded))


# ----------------------------------------------------------- nucleus (top-p)


class TestTopP:
    """Nucleus sampling (VERDICT r04 item 6): the filter keeps the smallest
    token set reaching top_p cumulative mass (crossing token included, >=1
    survivor), samples only from it, and is mesh-consistent."""

    def _kept(self, filtered):
        return np.isfinite(np.asarray(filtered))

    def test_filter_keeps_minimal_nucleus(self):
        from distributed_pytorch_tpu.generation import top_p_filter

        logits = jnp.log(jnp.array([[0.5, 0.3, 0.15, 0.05]]))
        # cumulative mass BEFORE each token: 0, .5, .8, .95
        np.testing.assert_array_equal(
            self._kept(top_p_filter(logits, 0.8))[0],
            [True, True, False, False],
        )
        np.testing.assert_array_equal(
            self._kept(top_p_filter(logits, 0.81))[0],
            [True, True, True, False],
        )
        np.testing.assert_array_equal(
            self._kept(top_p_filter(logits, 0.999))[0],
            [True, True, True, True],
        )
        # Tiny top_p: the argmax always survives.
        np.testing.assert_array_equal(
            self._kept(top_p_filter(logits, 1e-6))[0],
            [True, False, False, False],
        )

    def test_filter_is_order_invariant(self):
        from distributed_pytorch_tpu.generation import top_p_filter

        base = jnp.log(jnp.array([0.4, 0.25, 0.2, 0.1, 0.05]))
        perm = jnp.array([3, 0, 4, 2, 1])
        filtered = top_p_filter(base[perm][None, :], 0.7)
        # Nucleus of the sorted dist is {0.4, 0.25, 0.2} (cum-before .65 < .7
        # for the third); the same tokens must survive any input order.
        np.testing.assert_array_equal(
            self._kept(filtered)[0],
            np.asarray([False, True, False, True, True]),
        )

    def test_filter_keeps_per_row_nuclei(self):
        from distributed_pytorch_tpu.generation import top_p_filter

        logits = jnp.log(
            jnp.array([[0.97, 0.01, 0.01, 0.01], [0.25, 0.25, 0.25, 0.25]])
        )
        kept = self._kept(top_p_filter(logits, 0.5))
        np.testing.assert_array_equal(kept[0], [True, False, False, False])
        # Uniform row: 0.5 mass needs two tokens, but boundary TIES are all
        # kept (documented convention).
        assert kept[1].all()

    def test_samples_stay_inside_nucleus(self):
        from distributed_pytorch_tpu.generation import top_p_filter

        rng = np.random.default_rng(3)
        logits = jnp.asarray(rng.standard_normal((4, 32)), jnp.float32)
        filtered = top_p_filter(logits, 0.6)
        kept = self._kept(filtered)
        draws = jax.vmap(
            lambda key: jax.random.categorical(key, filtered, axis=-1)
        )(jax.random.split(jax.random.PRNGKey(0), 64))
        for row in range(4):
            assert kept[row, np.asarray(draws)[:, row]].all()

    def test_generate_top_p_shapes_and_mesh_parity(self):
        """Sampled decode with top_p runs end to end, respects vocab bounds,
        and the mesh path reproduces the single-device tokens at the same
        rng."""
        from distributed_pytorch_tpu.parallel.mesh import make_mesh

        model = tiny_lm()
        params, _ = make_params(model, batch=8, seq=6)
        prompt = jnp.asarray(
            np.random.default_rng(5).integers(0, 48, (8, 6)), jnp.int32
        )
        kw = dict(
            temperature=1.0, top_p=0.8, top_k=16, rng=jax.random.PRNGKey(11)
        )
        single = generate(model, params, prompt, 7, **kw)
        assert single.shape == (8, 13)
        assert (np.asarray(single) >= 0).all()
        assert (np.asarray(single) < 48).all()
        mesh = make_mesh({"data": 8})
        sharded = generate(model, params, prompt, 7, mesh=mesh, **kw)
        np.testing.assert_array_equal(np.asarray(sharded), np.asarray(single))

    def test_truncate_logits_matches_sequential_filters(self):
        """The fused single-sort path (decode hot loop) must keep exactly
        the tokens that top-k masking followed by top_p_filter over the
        renormalized survivors keeps."""
        from distributed_pytorch_tpu.generation import (
            top_p_filter,
            truncate_logits,
        )

        rng = np.random.default_rng(9)
        logits = jnp.asarray(rng.standard_normal((5, 64)), jnp.float32)
        # Integer-valued logits force TIES, including at the k-th largest —
        # the case where a naive exactly-k nucleus diverges from the
        # documented tie-inclusive semantics (bf16/quantized models tie
        # often). [2,1,1,1] with top_k=2 is the canonical counterexample.
        tied = jnp.asarray(
            np.concatenate(
                [
                    rng.integers(-2, 3, (4, 64)).astype(np.float32),
                    np.array([[2.0, 1.0, 1.0, 1.0] + [0.0] * 60]),
                ]
            )
        )
        for top_k, top_p in [(0, 0.7), (8, 0.0), (8, 0.7), (3, 0.95), (64, 0.5), (2, 0.6)]:
            for case, arr in (("continuous", logits), ("tied", tied)):
                fused = np.isfinite(
                    np.asarray(truncate_logits(arr, top_k, top_p))
                )
                ref = arr
                if top_k > 0:
                    kth = jnp.sort(ref, axis=-1)[:, -top_k][:, None]
                    ref = jnp.where(ref < kth, -jnp.inf, ref)
                if 0.0 < top_p < 1.0:
                    ref = top_p_filter(ref, top_p)
                np.testing.assert_array_equal(
                    fused, np.isfinite(np.asarray(ref)),
                    err_msg=f"{case} top_k={top_k} top_p={top_p}",
                )

    def test_top_k_beyond_vocab_keeps_everything(self):
        """top_k > vocab must degrade to keep-all (the pre-fusion behavior),
        not crash on an empty slice."""
        from distributed_pytorch_tpu.generation import truncate_logits

        logits = jnp.asarray(
            np.random.default_rng(1).standard_normal((2, 8)), jnp.float32
        )
        out = truncate_logits(logits, 100, 0.0)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(logits))
        # ...and still composes with a nucleus.
        out_p = truncate_logits(logits, 100, 0.5)
        assert np.isfinite(np.asarray(out_p)).sum() < logits.size


# ----------------------------------------------------- zero-length prompts


class TestZeroLengthPrompt:
    """Regression: a zero-length row in ``prompt_lengths`` must not push the
    bucketed prefill below 1 (the serial loop's body at position t decides
    token t+1, so position 0 must go through the loop — a prefill of 0 would
    try to compile a [B, 0] apply)."""

    def test_bucketed_prefill_len_clamps_zero_to_one(self):
        from distributed_pytorch_tpu.generation import bucketed_prefill_len

        assert bucketed_prefill_len([0, 6]) == 1
        assert bucketed_prefill_len([0]) == 1
        assert bucketed_prefill_len([1, 9]) == 1  # pow2 floor of min
        assert bucketed_prefill_len([6, 9]) == 4

    def test_negative_prompt_length_raises(self):
        from distributed_pytorch_tpu.generation import bucketed_prefill_len

        with pytest.raises(ValueError):
            bucketed_prefill_len([-1, 6])

    def test_zero_length_row_does_not_perturb_others(self):
        """A batch containing a zero-length prompt generates, and the
        full-prompt row's output is identical to running it alone."""
        model = tiny_lm()
        params, tokens = make_params(model, batch=2, seq=6)
        lengths = jnp.asarray([6, 0], jnp.int32)
        out = np.asarray(
            generate(
                model, params, jnp.asarray(tokens), 4,
                prompt_lengths=lengths,
            )
        )
        solo = np.asarray(
            generate(model, params, jnp.asarray(tokens[:1]), 4)
        )
        np.testing.assert_array_equal(out[0], solo[0])
        assert out.shape == (2, 10)
        assert (out >= 0).all() and (out < 48).all()
