"""StepProfiler tests (SURVEY.md §5 tracing/profiling).

The reference demonstrably writes ``./log/resnet50/<device>.pt.trace.json``
via ``torch.profiler`` with a wait=1/warmup=1/active=5 step schedule
(reference ``multigpu_profile.py:80-91``). These tests pin the same contract
for our TPU-native twin: the schedule window is honored, and a non-empty
XPlane trace artifact lands under ``<logdir>/host_<n>/``.
"""

import glob
import os

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu.profiling import StepProfiler


def run_steps(profiler: StepProfiler, n: int) -> list:
    """Drive ``n`` trivial jitted steps through the profiler's hook points
    (the Trainer's placement: step compute, sync, ``profiler.step()``)."""
    step = jax.jit(lambda x: (x * 2.0).sum())
    tracing_at = []
    profiler.start()
    for i in range(n):
        jax.block_until_ready(step(jnp.arange(8.0) + i))
        tracing_at.append(profiler._tracing)
        profiler.step()
    profiler.stop()
    return tracing_at


def trace_files(logdir: str) -> list:
    """Every file the trace produced under the per-host subdir."""
    return [
        p
        for p in glob.glob(os.path.join(logdir, "host_*", "**", "*"), recursive=True)
        if os.path.isfile(p)
    ]


class TestStepProfiler:
    def test_writes_nonempty_trace(self, tmp_path):
        """A full wait/warmup/active window produces a non-empty XPlane
        artifact (twin of the reference's ``.pt.trace.json`` evidence)."""
        logdir = str(tmp_path / "log")
        profiler = StepProfiler(logdir, wait=1, warmup=1, active=3)
        run_steps(profiler, 8)
        files = trace_files(logdir)
        assert files, f"no trace files under {logdir}"
        xplanes = [p for p in files if p.endswith(".xplane.pb")]
        assert xplanes, f"no .xplane.pb among {files}"
        assert all(os.path.getsize(p) > 0 for p in xplanes)

    def test_schedule_window_honored(self, tmp_path):
        """Tracing is off for wait+warmup steps, on for exactly ``active``
        steps, then off again — the torch.profiler schedule semantics."""
        profiler = StepProfiler(str(tmp_path / "log"), wait=2, warmup=1, active=3)
        tracing_at = run_steps(profiler, 10)
        # _tracing is sampled after compute, before profiler.step(): steps
        # 0..2 are wait+warmup (off), 3..5 active (on), 6+ off.
        assert tracing_at == [False] * 3 + [True] * 3 + [False] * 4

    def test_stop_closes_short_window(self, tmp_path):
        """An epoch shorter than wait+warmup+active must still finalize the
        trace on stop() (no dangling start_trace)."""
        logdir = str(tmp_path / "log")
        profiler = StepProfiler(logdir, wait=0, warmup=1, active=100)
        run_steps(profiler, 3)  # stop() lands mid-active-window
        assert not profiler._tracing
        assert any(p.endswith(".xplane.pb") for p in trace_files(logdir))

    def test_annotations_do_not_disturb_window(self, tmp_path):
        """With per-step StepTraceAnnotation markers on (the default), the
        wait/warmup/active window transitions exactly as without them, every
        annotation is closed by stop(), and the trace still lands."""
        logdir = str(tmp_path / "log")
        profiler = StepProfiler(
            logdir, wait=2, warmup=1, active=3, annotate=True
        )
        step = jax.jit(lambda x: (x * 2.0).sum())
        tracing_at, annotated_at = [], []
        profiler.start()
        for i in range(10):
            jax.block_until_ready(step(jnp.arange(8.0) + i))
            tracing_at.append(profiler._tracing)
            annotated_at.append(profiler._annotation is not None)
            profiler.step()
        profiler.stop()
        assert tracing_at == [False] * 3 + [True] * 3 + [False] * 4
        # An annotation is open exactly while the trace is live — never
        # outside the window, never left dangling after stop().
        assert annotated_at == tracing_at
        assert profiler._annotation is None, "annotation leaked past stop()"
        assert any(p.endswith(".xplane.pb") for p in trace_files(logdir))

    def test_annotations_off_matches_legacy(self, tmp_path):
        """``annotate=False`` keeps the bare pre-annotation behavior: the
        identical schedule window and no annotation object ever created."""
        profiler = StepProfiler(
            str(tmp_path / "log"), wait=1, warmup=1, active=2, annotate=False
        )
        tracing_at = run_steps(profiler, 6)
        assert tracing_at == [False] * 2 + [True] * 2 + [False] * 2
        assert profiler._annotation is None

    def test_rewind_mid_window_keeps_trace_alive(self, tmp_path):
        """An elastic restore that rewinds INSIDE the live window must not
        stop the trace; the schedule closes it at the original end step of
        the replayed timeline."""
        profiler = StepProfiler(
            str(tmp_path / "log"), wait=1, warmup=1, active=4
        )  # window is steps [2, 6)
        step = jax.jit(lambda x: (x * 2.0).sum())
        profiler.start()
        for i in range(4):  # lands at step 4, mid-window
            jax.block_until_ready(step(jnp.arange(8.0) + i))
            profiler.step()
        assert profiler._tracing
        profiler.rewind(3)  # restore inside the window: keep tracing
        assert profiler._tracing
        assert profiler._annotation is not None
        profiler.rewind(profiler._step)  # idempotent under a no-op rewind
        assert profiler._tracing
        tracing_at = []
        for i in range(4):
            jax.block_until_ready(step(jnp.arange(8.0) + i))
            tracing_at.append(profiler._tracing)
            profiler.step()
        profiler.stop()
        # replayed steps 3..5 traced, 6 past the window end
        assert tracing_at == [True, True, True, False]
        assert profiler._annotation is None

    def test_rewind_after_window_rearms_trace(self, tmp_path):
        """A restore that rewinds back INTO an already-closed window
        re-arms the schedule: the trace starts again and closes at the
        window end a second time."""
        logdir = str(tmp_path / "log")
        profiler = StepProfiler(logdir, wait=0, warmup=1, active=2)
        step = jax.jit(lambda x: (x * 2.0).sum())
        profiler.start()
        for i in range(5):  # window [1, 3) opens and closes
            jax.block_until_ready(step(jnp.arange(8.0) + i))
            profiler.step()
        assert not profiler._tracing
        assert any(p.endswith(".xplane.pb") for p in trace_files(logdir))
        profiler.rewind(1)  # snapshot resume from inside the window
        assert profiler._tracing, "rewind into the window must re-arm"
        tracing_at = []
        for i in range(4):
            jax.block_until_ready(step(jnp.arange(8.0) + i))
            tracing_at.append(profiler._tracing)
            profiler.step()
        profiler.stop()
        assert tracing_at == [True, True, False, False]
        assert not profiler._tracing

    def test_rewind_before_window_stops_trace_cleanly(self, tmp_path):
        """A restore to a step BEFORE the window stops a live trace (and
        its annotation) immediately; the replayed timeline re-enters the
        window at the original begin step."""
        profiler = StepProfiler(
            str(tmp_path / "log"), wait=2, warmup=1, active=2
        )  # window [3, 5)
        step = jax.jit(lambda x: (x * 2.0).sum())
        profiler.start()
        for i in range(4):  # step 4: tracing
            jax.block_until_ready(step(jnp.arange(8.0) + i))
            profiler.step()
        assert profiler._tracing
        profiler.rewind(0)  # snapshot predates the window
        assert not profiler._tracing
        assert profiler._annotation is None, "annotation leaked past rewind"
        tracing_at = []
        for i in range(6):
            jax.block_until_ready(step(jnp.arange(8.0) + i))
            tracing_at.append(profiler._tracing)
            profiler.step()
        profiler.stop()
        assert tracing_at == [False] * 3 + [True, True, False]

    def test_trace_contains_step_ops(self, tmp_path):
        """The captured trace is parseable and non-trivial: it contains
        XLA execution events from the profiled steps."""
        pytest.importorskip("jax.profiler", reason="ProfileData needs jax")
        from jax.profiler import ProfileData

        logdir = str(tmp_path / "log")
        profiler = StepProfiler(logdir, wait=1, warmup=1, active=2)
        run_steps(profiler, 6)
        xplanes = [
            p for p in trace_files(logdir) if p.endswith(".xplane.pb")
        ]
        assert xplanes
        data = ProfileData.from_serialized_xspace(open(xplanes[0], "rb").read())
        n_events = sum(
            sum(len(list(line.events)) for line in plane.lines)
            for plane in data.planes
        )
        assert n_events > 0, "trace parsed but contains no events"
