"""Cross-process fleet drills: REAL faults against worker subprocesses.

The in-process fleet tests (``test_serving_fleet.py``) prove the routing
and failover logic against simulated faults. This file runs the same
drills against ``ProcessReplicaClient`` workers where the fault is the
real thing:

* ``kill_replica_process`` delivers an actual SIGKILL to a loaded worker
  mid-decode — detection is a failed control call, recovery is shadow
  re-admission on a survivor, and the acceptance bar is unchanged:
  greedy-token parity with an uninterrupted single-engine reference,
  zero referenced pages on every survivor, one trace_id spanning the
  victim's lanes and the survivor's.
* ``hang_replica_process`` delivers SIGSTOP — the "hung but alive" fault
  the circuit breaker exists for: calls time out, the breaker opens,
  routing degrades around the replica WITHOUT declaring it dead, and
  when SIGCONT lands the half-open probe re-admits it with no request
  lost and no token emitted twice.

All slow (each spawns JAX subprocesses); the fleet-chaos CI job runs
them alongside ``tools/fleet_smoke.sh procs``.
"""

import json
import os
import random

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu import chaos
from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.obs import Tracer, merge_traces
from distributed_pytorch_tpu.serving import (
    AutoscalePolicy,
    FleetRouter,
    InferenceEngine,
    ProcessReplicaClient,
    SamplingParams,
    prefix_affinity_key,
    spawn_replica_clients,
)
from distributed_pytorch_tpu.serving.fleet import ID_STRIDE, _rendezvous

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

MODEL_KW = dict(
    vocab_size=48, d_model=16, n_layers=1, n_heads=2, d_ff=32,
)
ENGINE_KW = dict(
    max_slots=2, max_seq_len=32, page_size=4, token_budget=16,
    max_prefill_chunk=8, debug=True,
)
MAX_NEW = 6
PAGE = ENGINE_KW["page_size"]

PREFIX = [5, 7, 11, 2]
AFFINITY_PROMPTS = [PREFIX + [t, t + 1] for t in (1, 9, 17, 25, 33)]
OTHER_PROMPTS = [[2, 2, 3, 17, 40], [6, 1, 9], [40, 41], [3, 3, 3, 3, 8]]
DRILL_PROMPTS = AFFINITY_PROMPTS + OTHER_PROMPTS


def worker_spec(name, **extra):
    spec = {
        "name": name,
        "model": dict(MODEL_KW, dtype="float32"),
        "init_seed": 0,
        "engine": ENGINE_KW,
        "trace": True,
        "trace_every": 1,  # piggyback a trace doc on EVERY step response
    }
    spec.update(extra)
    return spec


def params_for(i):
    return SamplingParams(max_new_tokens=MAX_NEW)


@pytest.fixture(autouse=True)
def _fresh_chaos_plan():
    chaos._reset()
    yield
    os.environ.pop(chaos.ENV_VAR, None)
    chaos._reset()


def arm(plan):
    os.environ[chaos.ENV_VAR] = json.dumps(plan)
    chaos._reset()


@pytest.fixture(scope="module")
def ref_outputs():
    """Uninterrupted single-engine reference, in-parent, from the same
    init seed the workers build from — token parity across the process
    boundary is exact."""
    model = TransformerLM(**MODEL_KW, dtype=jnp.float32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    eng = InferenceEngine(model, params, **ENGINE_KW)
    ids = [
        eng.submit(p, params_for(i)) for i, p in enumerate(DRILL_PROMPTS)
    ]
    eng.run()
    out = {i: eng.poll(rid).generated for i, rid in enumerate(ids)}
    eng.close()
    return out


def assert_parity(router, fids_by_prompt_idx, ref_outputs):
    for idx, fid in fids_by_prompt_idx.items():
        st = router.poll(fid)
        assert st.finished, f"prompt {idx} (fid {fid}) never finished"
        assert list(st.generated) == list(ref_outputs[idx]), (
            f"prompt {idx}: fleet produced {st.generated}, "
            f"reference {ref_outputs[idx]}"
        )


# ------------------------------------------------------ the headline drill


def test_process_fleet_kill_drill(ref_outputs):
    """SIGKILL a loaded replica PROCESS mid-decode under seeded Poisson
    load: union token parity, zero survivor page leaks, one trace_id
    spanning victim and survivor lanes."""
    key = prefix_affinity_key(AFFINITY_PROMPTS[0], PAGE)
    victim = _rendezvous(key, ["r0", "r1", "r2"])
    victim_idx = int(victim[1:])
    arm({
        "seed": 1234,
        "faults": [
            {"kind": "kill_replica_process", "replica": victim_idx,
             "at_step": 3}
        ],
    })
    clients = spawn_replica_clients(
        [worker_spec(f"r{i}") for i in range(3)]
    )
    router = FleetRouter(clients, probe_every=2, tracer=Tracer())
    rng = random.Random(1234)
    schedule = {}
    rnd = 0
    for idx in range(len(DRILL_PROMPTS)):
        schedule.setdefault(rnd, []).append(idx)
        while rng.random() < 0.5:
            rnd += 1
    fids = {}
    try:
        rounds = 0
        while True:
            for idx in schedule.pop(rounds, []):
                fids[idx] = router.submit(
                    DRILL_PROMPTS[idx], params_for(idx)
                )
            done = not schedule and all(
                s.finished for s in router._shadows.values()
            )
            if done and len(fids) == len(DRILL_PROMPTS):
                break
            router.step()
            rounds += 1
            assert rounds < 500, "drill did not converge"

        dead = [r for r in router.replicas() if r.state == "dead"]
        assert [r.name for r in dead] == [victim]
        assert dead[0].dead_reason == "kill_replica_process"
        # The kill was real: the worker process is gone (SIGKILL = -9).
        assert clients[victim_idx]._proc.poll() == -9
        assert (
            router.registry.read_counter("requests_failed_over_total") >= 1
        )
        assert (
            router.registry.read_gauge("dead_replica_detection_seconds")
            >= 0.0
        )
        assert_parity(router, fids, ref_outputs)
        # Zero leaked pages on every survivor — read over the wire.
        for rep in router.replicas():
            if rep.state == "dead":
                continue
            assert rep.client.read_gauge("pages_referenced") == 0, (
                f"{rep.name} leaked referenced pages"
            )

        # One trace identity spans the failover: the victim's lanes come
        # from the client's LAST piggybacked trace snapshot (the process
        # is dead; nothing else could have them), the survivor's from a
        # live scrape — merged, the moved request's trace_id opens spans
        # in at least two distinct process lanes.
        moved = [
            fid for fid, s in router._shadows.items() if s.failovers > 0
        ]
        assert moved, "kill landed but nothing failed over"
        tid = router._shadows[moved[0]].trace_id
        merged = merge_traces(*router.trace_documents())
        opened_pids = {
            e["pid"]
            for e in merged["traceEvents"]
            if e.get("ph") == "b"
            and e.get("args", {}).get("trace_id") == tid
        }
        assert len(opened_pids) >= 2, (
            f"trace {tid} only spans lanes {sorted(opened_pids)}"
        )
    finally:
        router.close()


# ------------------------------------------------- breaker degraded mode


def test_process_breaker_sigstop_degrade_and_rejoin(ref_outputs):
    """SIGSTOP the loaded worker: its calls time out, the breaker opens
    within the deadline budget, routing excludes it WITHOUT declaring it
    dead; after SIGCONT the half-open probe closes the breaker and it
    rejoins — every request finishes exactly once, token-identical."""
    clients = spawn_replica_clients(
        [worker_spec(f"r{i}") for i in range(2)],
        call_timeout_s=0.5,
        call_retries=1,
        breaker_fail_threshold=2,
        breaker_reset_s=0.4,
    )
    router = FleetRouter(clients, probe_every=2, probe_timeout_s=0.5)
    fids = {}
    emitted = []
    try:
        for idx in range(4):
            fids[idx] = router.submit(
                DRILL_PROMPTS[idx], params_for(idx)
            )
        emitted.extend(router.step())
        victim_name = router._shadows[fids[0]].replica
        victim = router._by_name[victim_name]
        victim_idx = int(victim_name[1:])
        assert any(
            not s.finished and s.replica == victim_name
            for s in router._shadows.values()
        ), "victim must hold live work when the hang lands"

        # Deliver the real SIGSTOP (auto-SIGCONT after 2s).
        router._apply_fault(chaos.Fault(
            kind="hang_replica_process", replica=victim_idx, duration=2.0,
        ))
        rounds = 0
        while victim.client.breaker.state != "open":
            emitted.extend(router.step())
            rounds += 1
            assert rounds < 20, "breaker never opened under SIGSTOP"

        # Degraded, not dead: excluded from routing, shadows intact.
        assert victim.state == "live"
        assert victim_name not in [
            r.name for r in router._eligible()
        ]
        for idx in range(4, len(DRILL_PROMPTS)):
            fids[idx] = router.submit(
                DRILL_PROMPTS[idx], params_for(idx)
            )
            assert router._shadows[fids[idx]].replica != victim_name, (
                "breaker-open replica must not take new work"
            )

        emitted.extend(router.run())

        assert victim.state == "live", "SIGSTOP must never declare death"
        assert victim.client.breaker.state == "closed"
        assert victim.client.breaker.opens_total >= 1
        assert victim.client.breaker.closes_total >= 1
        assert (
            router.registry.read_counter("requests_failed_over_total") == 0
        ), "nothing died, so nothing may fail over"
        # Exactly-once delivery across the blackout: the ack protocol
        # re-reports finishes whose responses were lost, the router
        # finalizes each fleet id once.
        assert sorted(emitted) == sorted(fids.values())
        assert_parity(router, fids, ref_outputs)
    finally:
        router.close()


# ------------------------------------------------------- autoscale spawns


def test_autoscale_spawns_process_replica():
    """The autoscaler graduates from constructing engines to spawning
    PROCESSES: scale-out calls ``replica_factory``, the new worker joins
    with its own id namespace; scale-in drains one cleanly over the
    control plane and its worker exits zero."""
    clients = spawn_replica_clients(
        [worker_spec(f"r{i}") for i in range(2)]
    )
    policy = AutoscalePolicy(min_replicas=1, max_replicas=3)
    router = FleetRouter(
        clients,
        autoscale=policy,
        replica_factory=lambda: ProcessReplicaClient(worker_spec("r2")),
    )
    try:
        # A firing burn-rate alert on r0 (the cached gauge the step
        # exchange would normally refresh).
        router._by_name["r0"].client._slo_firing = ["ttft_p95"]
        action = router.maybe_autoscale()
        assert action == ("out", "r2")
        grown = router._by_name["r2"]
        assert grown.client.is_process
        assert len(router._eligible()) == 3
        assert router.registry.read_counter("scale_outs_total") == 1
        # Fresh id namespace, enforced over the wire by /reserve_ids.
        rid = grown.client.submit([9, 4], SamplingParams(max_new_tokens=1))
        assert rid >= 2 * ID_STRIDE
        done = set()
        for _ in range(100):
            done.update(grown.client.step())
            if rid in done:
                break
        assert rid in done

        router._by_name["r0"].client._slo_firing = []
        for rep in router.replicas():
            rep.client._idle_fraction = 0.9
        action = router.maybe_autoscale()
        assert action is not None and action[0] == "in"
        assert router.registry.read_counter("scale_ins_total") == 1
        assert len(router._eligible()) == 2
        removed = action[1]
        # Clean drain: the removed worker was told to shut down and
        # exited ZERO (its leak asserts passed).
        assert router._by_name[removed].client._proc.wait(10) == 0
    finally:
        router.close()
