"""Speculative serving tests: greedy token-parity with the plain engine
across feature toggles and stress (mid-flight joins, preemption mid-verify,
prefix-cache-hit admission, rollback across copy-on-write), the sampled
marginal law, dual-pool page accounting, and metrics exposure. All on CPU
(conftest pins JAX_PLATFORMS=cpu), where the chunked verify logits match
the single-token decode bitwise at f32 — so greedy speculative serving is
asserted EXACTLY equal to the non-speculative engine, not approximately.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.serving import (
    InferenceEngine,
    SamplingParams,
)


def tiny_lm(n_layers=2, **kw):
    return TransformerLM(
        vocab_size=48, d_model=16, n_layers=n_layers, n_heads=2, d_ff=32,
        dtype=jnp.float32, **kw,
    )


@pytest.fixture(scope="module")
def target_and_params():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


@pytest.fixture(scope="module")
def draft_and_params():
    # A different (smaller, independently seeded) model: proposals rarely
    # match, exercising the rejection/rollback path hard.
    model = tiny_lm(n_layers=1)
    params = model.init(
        jax.random.PRNGKey(7), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


PROMPTS = [
    [5, 7, 11, 2, 9, 3],
    [1, 4, 8],
    [2, 2, 3, 17, 40],
    [6, 1, 9, 9],
]

ENGINE_KW = dict(
    max_slots=4, max_seq_len=32, page_size=4, token_budget=16,
    max_prefill_chunk=8, debug=True,
)


def run_engine(model, params, prompts, sp_list=None, draft=None, **kw):
    """Build an engine, submit every prompt, drain, return (per-request
    generated lists, engine)."""
    opts = dict(ENGINE_KW)
    opts.update(kw)
    if draft is not None:
        dmodel, dparams = draft
        opts.update(draft_model=dmodel, draft_params=dparams)
    eng = InferenceEngine(model, params, **opts)
    sp_list = sp_list or [
        SamplingParams(max_new_tokens=10) for _ in prompts
    ]
    ids = [eng.submit(p, sp) for p, sp in zip(prompts, sp_list)]
    eng.run()
    return [eng.poll(i).generated for i in ids], eng


def assert_no_leaks(eng):
    assert eng.allocator.num_allocated == 0, "pages leaked past drain"
    eng.allocator.check_invariants()


class TestGreedyParity:
    """Greedy speculative serving must be token-identical to the plain
    engine — per request, across every feature combination."""

    @pytest.mark.parametrize("gamma", [1, 3])
    @pytest.mark.parametrize("prefix_cache", [True, False])
    @pytest.mark.parametrize("overlap", [True, False])
    def test_matches_plain_engine(
        self, target_and_params, draft_and_params, gamma, prefix_cache,
        overlap,
    ):
        model, params = target_and_params
        plain, eng0 = run_engine(
            model, params, PROMPTS, prefix_cache=prefix_cache,
            overlap=overlap,
        )
        spec, eng = run_engine(
            model, params, PROMPTS, draft=draft_and_params, gamma=gamma,
            prefix_cache=prefix_cache, overlap=overlap,
        )
        assert spec == plain
        assert eng.stats()["verify_rounds"] > 0
        assert_no_leaks(eng)
        assert_no_leaks(eng0)

    def test_self_draft_accepts_everything(self, target_and_params):
        """draft == target: every proposal matches the target argmax, so
        acceptance is exactly 1.0 and each round advances by gamma."""
        model, params = target_and_params
        plain, _ = run_engine(model, params, PROMPTS)
        spec, eng = run_engine(
            model, params, PROMPTS, draft=(model, params), gamma=3,
        )
        assert spec == plain
        s = eng.stats()
        assert s["spec_acceptance_rate"] == pytest.approx(1.0)
        # 10 tokens per request at 3/round -> 4 rounds each, not 10.
        assert s["verify_rounds"] < s["tokens_generated"]
        assert_no_leaks(eng)

    def test_stop_token_truncates_mid_chunk(
        self, target_and_params,
    ):
        """A stop token landing inside an accepted chunk must end the
        request at exactly the same token as the plain engine — the round
        emits past it device-side and the host truncates."""
        model, params = target_and_params
        plain, _ = run_engine(model, params, PROMPTS)
        # Stop on a token the plain run actually generates mid-stream.
        stop = plain[0][4]
        sps = [
            SamplingParams(max_new_tokens=10, stop_token=stop)
            for _ in PROMPTS
        ]
        plain_stop, _ = run_engine(model, params, PROMPTS, sp_list=sps)
        # Self-draft so whole chunks are accepted (stop mid-chunk for sure).
        spec_stop, eng = run_engine(
            model, params, PROMPTS, sp_list=sps, draft=(model, params),
            gamma=4,
        )
        assert spec_stop == plain_stop
        assert_no_leaks(eng)

    def test_mid_flight_joins(self, target_and_params, draft_and_params):
        """Requests submitted while earlier ones are mid-verify join the
        batch without disturbing anyone's tokens."""
        model, params = target_and_params

        def staggered(draft):
            kw = dict(ENGINE_KW)
            if draft is not None:
                dm, dp = draft
                kw.update(draft_model=dm, draft_params=dp, gamma=3)
            eng = InferenceEngine(model, params, **kw)
            ids = []
            for prompt in PROMPTS:
                ids.append(
                    eng.submit(prompt, SamplingParams(max_new_tokens=8))
                )
                eng.step()  # earlier requests are mid-decode at each join
                eng.step()
            eng.run()
            return [eng.poll(i).generated for i in ids], eng

        plain, _ = staggered(None)
        spec, eng = staggered(draft_and_params)
        assert spec == plain
        assert_no_leaks(eng)

    def test_preemption_mid_verify(
        self, target_and_params, draft_and_params,
    ):
        """Page pressure (num_pages too small for all slots) forces
        preemption between verify rounds; evicted-and-resumed requests
        still reproduce the plain engine's tokens exactly."""
        model, params = target_and_params
        kw = dict(num_pages=17)  # 2 full sequences + 1 page of slack
        plain, eng0 = run_engine(model, params, PROMPTS, **kw)
        spec, eng = run_engine(
            model, params, PROMPTS, draft=draft_and_params, gamma=3, **kw
        )
        assert spec == plain
        assert eng.scheduler.preemptions > 0, (
            "fixture no longer forces preemption — shrink num_pages"
        )
        assert_no_leaks(eng)
        assert_no_leaks(eng0)

    def test_prefix_cache_hit_admission(self, target_and_params):
        """A request admitted entirely from cache (remaining_prefill == 0)
        enters DECODE immediately; its first speculative round must match
        the plain engine's continuation."""
        model, params = target_and_params
        prompt = PROMPTS[0]

        def twice(draft):
            kw = dict(ENGINE_KW)
            if draft is not None:
                kw.update(
                    draft_model=draft[0], draft_params=draft[1], gamma=3
                )
            eng = InferenceEngine(model, params, **kw)
            a = eng.submit(prompt, SamplingParams(max_new_tokens=8))
            eng.run()
            b = eng.submit(prompt, SamplingParams(max_new_tokens=8))
            eng.run()
            return eng.poll(a).generated, eng.poll(b).generated, eng

        pa, pb, _ = twice(None)
        sa, sb, eng = twice((model, params))
        assert pa == pb, "plain warm request diverged from cold"
        assert (sa, sb) == (pa, pb)
        assert eng.stats()["cached_tokens_admitted"] > 0, (
            "second submit did not hit the prefix cache"
        )
        assert_no_leaks(eng)

    def test_rollback_across_cow_page(
        self, target_and_params, draft_and_params,
    ):
        """Two multi-turn continuations extend the SAME cached partial
        page concurrently, then each runs speculative rounds that write
        (and partially reject) into its copy-on-write clone of that page —
        neither may perturb the other, and both match the plain engine."""
        model, params = target_and_params

        def multiturn(draft):
            kw = dict(ENGINE_KW)
            if draft is not None:
                dm, dp = draft
                kw.update(draft_model=dm, draft_params=dp, gamma=3)
            eng = InferenceEngine(model, params, **kw)
            base = [5, 7, 11, 2, 9]
            r0 = eng.submit(base, SamplingParams(max_new_tokens=2))
            eng.run()
            first = eng.poll(r0).generated
            # 6 cached tokens = 1 full page + 2 in the retired partial page
            hist = base + first[:1]
            ids = [
                eng.submit(hist + [t], SamplingParams(max_new_tokens=5))
                for t in (3, 17)
            ]
            eng.run()
            return [first] + [eng.poll(i).generated for i in ids], eng

        plain, _ = multiturn(None)
        spec, eng = multiturn(draft_and_params)
        assert spec == plain
        assert eng.scheduler.cow_copies >= 1, (
            "fixture no longer shares a partial page — adjust prompts"
        )
        assert_no_leaks(eng)


class TestSampledSpeculative:
    def test_marginal_law_matches_target(self, target_and_params):
        """Each sampled token must be exactly target-distributed. Pin the
        FIRST generated token's empirical law across many independently
        seeded requests against the target softmax, with a plain-engine
        control run calibrating the statistical bound."""
        model, params = target_and_params
        prompt = PROMPTS[0]
        n, temp = 400, 1.0

        logits = model.apply(
            {"params": params}, jnp.asarray([prompt], jnp.int32)
        )[0, -1]
        p = np.asarray(jax.nn.softmax(logits / temp), np.float64)

        def first_tokens(draft):
            kw = dict(ENGINE_KW)
            kw.update(max_slots=8, token_budget=64, prefix_cache=False)
            if draft is not None:
                kw.update(
                    draft_model=draft[0], draft_params=draft[1], gamma=2
                )
            eng = InferenceEngine(model, params, **kw)
            out = []
            ids = []
            for seed in range(n):
                ids.append(eng.submit(prompt, SamplingParams(
                    max_new_tokens=1, temperature=temp, seed=seed,
                )))
                eng.step()
            eng.run()
            for i in ids:
                out.append(eng.poll(i).generated[0])
            return np.bincount(out, minlength=48) / n

        # Draft = target params but a DIFFERENT tiny draft would also be
        # lawful; self-draft still exercises the accept/residual arithmetic
        # (u < min(1, p/q) with p == q accepts a.s.), while a second run
        # with a cold draft covers genuine rejections.
        cold = tiny_lm(n_layers=1)
        cold_params = cold.init(
            jax.random.PRNGKey(11), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        tv_spec = 0.5 * np.abs(first_tokens((cold, cold_params)) - p).sum()
        tv_plain = 0.5 * np.abs(first_tokens(None) - p).sum()
        # Same-n sampling noise baseline plus slack: the speculative law
        # may not be measurably farther from p than plain sampling.
        assert tv_spec < tv_plain + 0.15, (
            f"spec TV {tv_spec:.3f} vs plain TV {tv_plain:.3f}"
        )


class TestDualPoolAccounting:
    def test_randomized_cycles_leak_nothing(
        self, target_and_params, draft_and_params,
    ):
        """Randomized submit/step interleaving under page pressure: after
        every drain, zero pages allocated and allocator invariants hold —
        the one allocator governs both pools, so this is the draft-pool
        leak test too."""
        model, params = target_and_params
        rng = random.Random(0)
        eng = InferenceEngine(
            model, params, draft_model=draft_and_params[0],
            draft_params=draft_and_params[1], gamma=3, num_pages=19,
            **ENGINE_KW,
        )
        assert set(eng.pools.names) == {"target", "draft"}
        for cycle in range(4):
            for _ in range(rng.randrange(2, 6)):
                prompt = [
                    rng.randrange(1, 48)
                    for _ in range(rng.randrange(1, 9))
                ]
                eng.submit(prompt, SamplingParams(
                    max_new_tokens=rng.randrange(1, 8),
                    temperature=rng.choice([0.0, 0.9]),
                    seed=cycle,
                ))
                for _ in range(rng.randrange(3)):
                    eng.step()
            eng.run()
            assert_no_leaks(eng)

    def test_draft_pool_geometry_matches_target(
        self, target_and_params, draft_and_params,
    ):
        """Lockstep needs identical (num_pages, page_size) in both pools;
        head/width may differ."""
        model, params = target_and_params
        eng = InferenceEngine(
            model, params, draft_model=draft_and_params[0],
            draft_params=draft_and_params[1], gamma=2, **ENGINE_KW,
        )
        t_pool = jax.tree_util.tree_leaves(eng.cache)[0]
        d_pool = jax.tree_util.tree_leaves(eng.draft_cache)[0]
        assert t_pool.shape[:2] == d_pool.shape[:2]

    def test_vocab_mismatch_rejected(self, target_and_params):
        model, params = target_and_params
        bad = TransformerLM(
            vocab_size=32, d_model=16, n_layers=1, n_heads=2, d_ff=32,
            dtype=jnp.float32,
        )
        bad_params = bad.init(
            jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        with pytest.raises(ValueError, match="vocab"):
            InferenceEngine(
                model, params, draft_model=bad, draft_params=bad_params,
                **ENGINE_KW,
            )


class TestSpecMetrics:
    def test_stats_surface(self, target_and_params, draft_and_params):
        model, params = target_and_params
        _, eng = run_engine(
            model, params, PROMPTS, draft=draft_and_params, gamma=3,
        )
        s = eng.stats()
        assert s["verify_rounds"] > 0
        assert s["draft_tokens_proposed"] == 3 * s["verify_rounds"]
        assert 0.0 <= s["spec_acceptance_rate"] <= 1.0
        assert s["spec_acceptance_rate_count"] == s["verify_rounds"]
        assert s["spec_tokens_per_verify_count"] == s["verify_rounds"]
        assert 1.0 <= s["spec_tokens_per_verify_mean"] <= 3.0
        # TPOT lands in the "spec" mode reservoir, never "plain".
        assert s["tpot_s_spec_count"] > 0
        assert s["tpot_s_plain_count"] == 0

    def test_plain_engine_reports_no_spec_metrics(self, target_and_params):
        model, params = target_and_params
        _, eng = run_engine(model, params, PROMPTS)
        s = eng.stats()
        assert "verify_rounds" not in s
        assert s["tpot_s_plain_count"] > 0
        assert s["tpot_s_spec_count"] == 0
