"""Test bootstrap: force an 8-device virtual CPU backend BEFORE jax imports.

This is the TPU-world stand-in for a multi-chip test rig (SURVEY.md §4):
``--xla_force_host_platform_device_count=8`` gives 8 CPU "devices", so
mesh/sharding/collective tests (the ``multigpu.py`` tier of the reference)
run on one host in CI.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The environment may ship a platform plugin (e.g. the experimental "axon" TPU
# tunnel) that overrides JAX_PLATFORMS; pin the config explicitly before any
# backend is initialized.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Make the repo importable without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process / subprocess integration tests"
    )
    config.addinivalue_line(
        "markers",
        "chaos: deterministic fault-injection tests (the chaos harness); "
        "fast CPU-only injections run in tier-1, long drills are also "
        "marked slow",
    )
