"""Cross-framework parity oracle: the framework's toy-regression training
must reproduce the PyTorch reference's loss curve step for step.

SURVEY.md §4's "parity oracle": torch (CPU) IS the reference implementation —
``Linear(20, 1)`` + SGD(lr=1e-3) + MSE, the exact workload of
``multinode_torchrun.py`` (the one reference rung whose loss matches its
regression head, ``multinode_torchrun.py:46``). With identical init, identical
batch order, and DDP's mean-of-grads semantics, the jitted SPMD train step
must produce the same losses:

* serial (1 device)       == torch single-process (``single_gpu.py`` tier);
* 4-way data parallel     == torch DDP mean-of-grads over the same global
  batch (``multigpu.py`` tier) — here torch's DDP allreduce is emulated
  exactly by computing the full-batch gradient, which equals the mean of
  per-shard gradients for MSE over equal shards.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
import torch

from distributed_pytorch_tpu.models import ToyRegressor
from distributed_pytorch_tpu.parallel.mesh import make_mesh
from distributed_pytorch_tpu.parallel.sharding import (
    put_global_batch,
    replicated_sharding,
)
from distributed_pytorch_tpu.training.losses import mse_loss
from distributed_pytorch_tpu.training.train_step import (
    create_train_state,
    make_train_step,
)
from distributed_pytorch_tpu.utils.data import MaterializedDataset, ShardedLoader

LR = 1e-3
STEPS = 30
BATCH = 32


def make_batches():
    """Deterministic batch stream shared by both frameworks."""
    data = MaterializedDataset(2048, seed=0)
    loader = ShardedLoader(data, BATCH, shuffle=True, seed=0)
    loader.set_epoch(0)
    return [(xs.copy(), ys.copy()) for xs, ys in loader][:STEPS]


def torch_curve(batches):
    """The reference implementation, verbatim semantics: Linear(20,1), MSE,
    SGD(lr=1e-3), full-batch gradient (== DDP mean-of-grads)."""
    torch.manual_seed(0)
    model = torch.nn.Linear(20, 1)
    opt = torch.optim.SGD(model.parameters(), lr=LR)
    loss_fn = torch.nn.MSELoss()
    weight0 = model.weight.detach().numpy().copy()
    bias0 = model.bias.detach().numpy().copy()
    losses = []
    for xs, ys in batches:
        opt.zero_grad()
        loss = loss_fn(model(torch.from_numpy(xs)), torch.from_numpy(ys))
        loss.backward()
        opt.step()
        losses.append(float(loss.detach()))
    return np.asarray(losses), weight0, bias0


def jax_curve(batches, weight0, bias0, n_devices=1):
    model = ToyRegressor()
    optimizer = optax.sgd(LR)
    state = create_train_state(model, optimizer, batches[0][0])
    # Identical init: adopt torch's initial weights (flax kernel is the
    # transpose of torch's [out, in] weight).
    params = {"linear": {"kernel": jnp.asarray(weight0.T), "bias": jnp.asarray(bias0)}}
    assert jax.tree_util.tree_structure(params) == jax.tree_util.tree_structure(
        state.params
    )
    state = state.replace(params=params, opt_state=optimizer.init(params))

    if n_devices > 1:
        mesh = make_mesh({"data": n_devices}, devices=jax.devices()[:n_devices])
        state = jax.device_put(state, replicated_sharding(mesh))
        step = make_train_step(model.apply, optimizer, mse_loss, mesh=mesh)
        put = lambda b: put_global_batch(mesh, b)  # noqa: E731
    else:
        step = make_train_step(model.apply, optimizer, mse_loss)
        put = jax.device_put

    losses = []
    for xs, ys in batches:
        state, loss = step(state, put((xs, ys)))
        losses.append(float(loss))
    return np.asarray(losses)


@pytest.mark.parametrize("n_devices", [1, 4])
def test_loss_curve_matches_torch(n_devices):
    batches = make_batches()
    ref, weight0, bias0 = torch_curve(batches)
    ours = jax_curve(batches, weight0, bias0, n_devices=n_devices)
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)


def test_sharded_grads_equal_ddp_mean_of_grads():
    """DDP averages per-rank gradients of per-rank mean losses; our global-
    batch mean loss has the same gradient when shards are equal — verify the
    8-way sharded step and the serial step produce identical updates."""
    batches = make_batches()[:5]
    _, weight0, bias0 = torch_curve(batches)
    serial = jax_curve(batches, weight0, bias0, n_devices=1)
    sharded = jax_curve(batches, weight0, bias0, n_devices=8)
    np.testing.assert_allclose(sharded, serial, rtol=1e-6)
