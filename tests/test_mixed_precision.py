"""Mixed-precision policy + loss scaling.

The reference trains fp32 end to end and never touches AMP; this is the
TPU-framework's precision story: bf16/f32 policy objects, and fp16-grade
loss scaling with GradScaler semantics (scale the loss, unscale the grads,
skip non-finite updates, halve/grow the scale) fused into the jitted step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_pytorch_tpu.models import ToyRegressor
from distributed_pytorch_tpu.training.losses import mse_loss
from distributed_pytorch_tpu.training.mixed_precision import (
    BF16_POLICY,
    FP16_POLICY,
    DynamicLossScale,
    Policy,
    StaticLossScale,
    all_finite,
)
from distributed_pytorch_tpu.training.train_step import (
    create_train_state,
    make_train_step,
)


def toy_batches(n=6, batch=32, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.standard_normal((20, 1)).astype(np.float32)
    xs = rng.standard_normal((n, batch, 20)).astype(np.float32)
    ys = xs @ w + 0.01 * rng.standard_normal((n, batch, 1)).astype(np.float32)
    return [(jnp.asarray(x), jnp.asarray(y)) for x, y in zip(xs, ys)]


def build(loss_scale=None, grad_accum=1):
    model = ToyRegressor()
    opt = optax.sgd(1e-2)
    batches = toy_batches()
    state = create_train_state(model, opt, batches[0][0], loss_scale=loss_scale)
    step = make_train_step(model.apply, opt, mse_loss, grad_accum=grad_accum)
    return state, step, batches


class TestPolicy:
    def test_cast_helpers_touch_only_floats(self):
        tree = {
            "w": jnp.ones((2, 2), jnp.float32),
            "i": jnp.ones((2,), jnp.int32),
            "b": jnp.array(True),
        }
        out = BF16_POLICY.cast_to_compute(tree)
        assert out["w"].dtype == jnp.bfloat16
        assert out["i"].dtype == jnp.int32
        assert out["b"].dtype == jnp.bool_
        back = BF16_POLICY.cast_to_param(out)
        assert back["w"].dtype == jnp.float32

    def test_named_policies(self):
        assert BF16_POLICY.compute_dtype == jnp.bfloat16
        assert BF16_POLICY.param_dtype == jnp.float32
        assert FP16_POLICY.compute_dtype == jnp.float16
        assert Policy().output_dtype == jnp.float32

    def test_all_finite(self):
        good = {"a": jnp.ones(3), "b": jnp.zeros(2)}
        assert bool(all_finite(good))
        bad = {"a": jnp.ones(3), "b": jnp.array([1.0, np.inf])}
        assert not bool(all_finite(bad))
        assert not bool(all_finite({"a": jnp.array([np.nan])}))


class TestStaticLossScale:
    def test_scaled_run_matches_unscaled(self):
        """Scale-then-unscale is exact in f32 for power-of-two scales: the
        whole loss curve must match the plain run bit-for-bit-ish."""
        state_a, step_a, batches = build()
        state_b, step_b, _ = build(loss_scale=StaticLossScale.create(1024.0))
        for batch in batches:
            state_a, loss_a = step_a(state_a, batch)
            state_b, loss_b = step_b(state_b, batch)
            np.testing.assert_allclose(
                float(loss_a), float(loss_b), rtol=1e-6
            )
        for pa, pb in zip(
            jax.tree_util.tree_leaves(state_a.params),
            jax.tree_util.tree_leaves(state_b.params),
        ):
            np.testing.assert_allclose(pa, pb, rtol=1e-5, atol=1e-7)

    def test_static_scale_survives_in_state(self):
        state, step, batches = build(loss_scale=StaticLossScale.create(256.0))
        state, _ = step(state, batches[0])
        assert float(state.loss_scale.scale) == 256.0


class TestDynamicLossScale:
    def test_overflow_skips_update_and_halves_scale(self):
        # A scale beyond f32 range makes the scaled loss (and thus the
        # gradients) overflow deterministically on the very first step.
        state, step, batches = build(
            loss_scale=DynamicLossScale.create(initial_scale=3e38)
        )
        params_before = jax.device_get(state.params)
        opt_before = jax.device_get(state.opt_state)
        state, _ = step(state, batches[0])
        for before, after in zip(
            jax.tree_util.tree_leaves(params_before),
            jax.tree_util.tree_leaves(jax.device_get(state.params)),
        ):
            np.testing.assert_array_equal(before, after)
        for before, after in zip(
            jax.tree_util.tree_leaves(opt_before),
            jax.tree_util.tree_leaves(jax.device_get(state.opt_state)),
        ):
            np.testing.assert_array_equal(before, after)
        assert int(state.step) == 1  # attempted steps still count
        assert float(state.loss_scale.scale) == pytest.approx(1.5e38)
        assert int(state.loss_scale.good_steps) == 0

    def test_growth_after_interval(self):
        state, step, batches = build(
            loss_scale=DynamicLossScale.create(
                initial_scale=8.0, growth_interval=2
            )
        )
        state, _ = step(state, batches[0])
        assert float(state.loss_scale.scale) == 8.0
        assert int(state.loss_scale.good_steps) == 1
        state, _ = step(state, batches[1])
        assert float(state.loss_scale.scale) == 16.0
        assert int(state.loss_scale.good_steps) == 0

    def test_scale_floor(self):
        ls = DynamicLossScale.create(initial_scale=1.5, min_scale=1.0)
        ls = ls.adjust(jnp.array(False))
        assert float(ls.scale) == 1.0
        ls = ls.adjust(jnp.array(False))
        assert float(ls.scale) == 1.0

    def test_fp16_compute_trains_under_dynamic_scale(self):
        """The actual fp16 use case: fp16 compute would underflow tiny
        gradients unscaled; with a dynamic scale the toy regression loss
        must fall."""
        model = ToyRegressor(dtype=jnp.float16)
        opt = optax.sgd(5e-2)
        batches = toy_batches()
        state = create_train_state(
            model,
            opt,
            batches[0][0],
            loss_scale=DynamicLossScale.create(initial_scale=2.0**10),
        )
        step = make_train_step(model.apply, opt, mse_loss)
        first = None
        for batch in batches * 5:
            state, loss = step(state, batch)
            first = float(loss) if first is None else first
        assert float(loss) < 0.2 * first

    def test_grad_accum_composes_with_scaling(self):
        state_a, step_a, batches = build(grad_accum=2)
        state_b, step_b, _ = build(
            loss_scale=StaticLossScale.create(512.0), grad_accum=2
        )
        for batch in batches:
            state_a, loss_a = step_a(state_a, batch)
            state_b, loss_b = step_b(state_b, batch)
            np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)
        for pa, pb in zip(
            jax.tree_util.tree_leaves(state_a.params),
            jax.tree_util.tree_leaves(state_b.params),
        ):
            np.testing.assert_allclose(pa, pb, rtol=1e-5, atol=1e-7)


class TestSnapshotRoundTrip:
    def test_loss_scale_checkpoints_with_state(self, tmp_path):
        from distributed_pytorch_tpu.checkpoint import (
            load_snapshot,
            save_snapshot,
        )

        state, step, batches = build(
            loss_scale=DynamicLossScale.create(
                initial_scale=32.0, growth_interval=1
            )
        )
        state, _ = step(state, batches[0])  # scale grows to 64
        path = str(tmp_path / "snap.npz")
        save_snapshot(path, state, epochs_run=3)
        template, _, _ = build(
            loss_scale=DynamicLossScale.create(
                initial_scale=32.0, growth_interval=1
            )
        )
        restored, meta = load_snapshot(path, template)
        assert meta["epochs_run"] == 3
        assert float(restored.loss_scale.scale) == float(state.loss_scale.scale)
        assert int(restored.loss_scale.good_steps) == int(
            state.loss_scale.good_steps
        )


class TestLossScaleOnMesh:
    def test_dynamic_scale_dp_parity(self):
        """Loss scaling composes with the data mesh: the scale replicates
        with the state, the finiteness check is a global reduction, and the
        loss curve matches the single-device scaled run exactly."""
        from distributed_pytorch_tpu.parallel.mesh import make_mesh
        from distributed_pytorch_tpu.parallel.sharding import (
            put_global_batch,
            replicated_sharding,
        )

        batches = toy_batches(n=4, batch=32)
        ls = lambda: DynamicLossScale.create(  # noqa: E731
            initial_scale=64.0, growth_interval=2
        )

        single_state, single_step, _ = build(loss_scale=ls())
        mesh = make_mesh({"data": 8})
        model = ToyRegressor()
        opt = optax.sgd(1e-2)
        mesh_state = create_train_state(
            model, opt, batches[0][0], loss_scale=ls()
        )
        mesh_state = jax.device_put(mesh_state, replicated_sharding(mesh))
        mesh_step = make_train_step(model.apply, opt, mse_loss, mesh=mesh)

        for batch in batches:
            single_state, loss_a = single_step(single_state, batch)
            mesh_state, loss_b = mesh_step(
                mesh_state, put_global_batch(mesh, batch)
            )
            np.testing.assert_allclose(
                float(loss_a), float(loss_b), rtol=1e-6
            )
        assert float(mesh_state.loss_scale.scale) == float(
            single_state.loss_scale.scale
        )
        assert float(mesh_state.loss_scale.scale) == 256.0  # grew twice
