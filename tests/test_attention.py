"""Ring attention correctness: sequence-parallel output must equal dense
attention on the full sequence, causal and not, on a dp x sp mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.ops.attention import (
    dot_product_attention,
    ring_attention,
)
from distributed_pytorch_tpu.parallel.mesh import make_mesh


def _qkv(b=4, t=32, h=2, d=8, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("mesh_axes", [{"sequence": 8}, {"data": 2, "sequence": 4}])
def test_ring_matches_dense(causal, mesh_axes):
    mesh = make_mesh(mesh_axes)
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh=mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ring_degenerates_on_trivial_axis():
    mesh = make_mesh({"data": 8, "sequence": 1})
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=True)
    out = ring_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_ring_gradients_match_dense():
    """d(loss)/d(q,k,v) must agree with dense attention — the backward pass is
    what training actually exercises."""
    mesh = make_mesh({"sequence": 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(b=2, t=16)

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh, causal=True) ** 2)

    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_dense, g_ring):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=1e-4, atol=1e-4)


def test_ring_falls_back_without_sequence_axis():
    """A mesh with no 'sequence' axis degrades to dense attention (no shard_map)."""
    mesh = make_mesh({"data": 8})
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=True)
    out = ring_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_submesh_requires_explicit_devices():
    with pytest.raises(ValueError, match="submesh"):
        make_mesh({"sequence": 4})


def test_dense_attention_causal_masking():
    """Output at position t must not depend on inputs at positions > t."""
    q, k, v = _qkv(b=1, t=8)
    out1 = dot_product_attention(q, k, v, causal=True)
    k2 = k.at[:, -1].set(100.0)
    v2 = v.at[:, -1].set(100.0)
    out2 = dot_product_attention(q, k2, v2, causal=True)
    np.testing.assert_allclose(
        np.asarray(out1[:, :-1]), np.asarray(out2[:, :-1]), rtol=1e-6
    )
    assert not np.allclose(np.asarray(out1[:, -1]), np.asarray(out2[:, -1]))


# ---------------------------------------------------------------------------
# Ring x flash composition (VERDICT round 1, item 3): each hop's local block
# through the Pallas kernel (interpret mode on CPU), fwd + grads.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_hops_match_dense(causal):
    mesh = make_mesh({"sequence": 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(b=2, t=64, h=2, d=16)
    ref = dot_product_attention(q, k, v, causal=causal)
    out = ring_attention(
        q, k, v, mesh=mesh, causal=causal, use_flash=True, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_ring_flash_gradients_match_dense():
    mesh = make_mesh({"sequence": 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(b=2, t=64, h=2, d=16)

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_attention(
                q, k, v, mesh=mesh, causal=True, use_flash=True, interpret=True
            )
            ** 2
        )

    ref = jax.grad(loss_dense, (0, 1, 2))(q, k, v)
    got = jax.grad(loss_ring, (0, 1, 2))(q, k, v)
    for a, b in zip(got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_ring_use_flash_rejects_untileable_local_block():
    mesh = make_mesh({"sequence": 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(b=2, t=20, h=2, d=8)  # T_local=5: no multiple-of-8 block
    with pytest.raises(ValueError, match="flash"):
        ring_attention(
            q, k, v, mesh=mesh, causal=True, use_flash=True, interpret=True
        )


# ----------------------------------------------------------------- ulysses


class TestUlyssesAttention:
    """All-to-all sequence parallelism (the ring's complement): seq->head
    redistribution, fully local attention, inverse exchange — must equal
    dense attention exactly, alone and composed with DP x TP."""

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize(
        "mesh_axes", [{"sequence": 8}, {"data": 2, "sequence": 2, "tensor": 2}]
    )
    def test_matches_dense(self, causal, mesh_axes):
        from distributed_pytorch_tpu.ops.attention import ulysses_attention

        mesh = make_mesh(mesh_axes)
        q, k, v = _qkv(b=2, t=32, h=8, d=8)
        ref = dot_product_attention(q, k, v, causal=causal)
        out = ulysses_attention(q, k, v, mesh=mesh, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_gradients_match_dense(self):
        from distributed_pytorch_tpu.ops.attention import ulysses_attention

        mesh = make_mesh({"sequence": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(b=2, t=32, h=4, d=8)

        def loss_dense(q, k, v):
            return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

        def loss_uly(q, k, v):
            return jnp.sum(
                ulysses_attention(q, k, v, mesh=mesh, causal=True) ** 2
            )

        ref = jax.grad(loss_dense, (0, 1, 2))(q, k, v)
        got = jax.grad(loss_uly, (0, 1, 2))(q, k, v)
        for a, b in zip(got, ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    @pytest.mark.slow
    def test_flash_path_matches_dense(self):
        from distributed_pytorch_tpu.ops.attention import ulysses_attention

        mesh = make_mesh({"sequence": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(b=2, t=64, h=4, d=16)
        ref = dot_product_attention(q, k, v, causal=True)
        out = ulysses_attention(
            q, k, v, mesh=mesh, causal=True, use_flash=True, interpret=True,
            block_q=16, block_k=16,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    def test_rejects_head_starved_config(self):
        from distributed_pytorch_tpu.ops.attention import ulysses_attention

        mesh = make_mesh({"sequence": 8})
        q, k, v = _qkv(b=2, t=32, h=4, d=8)  # 4 heads < sp=8
        with pytest.raises(ValueError, match="head count"):
            ulysses_attention(q, k, v, mesh=mesh, causal=True)

    def test_degenerates_on_trivial_axis(self):
        from distributed_pytorch_tpu.ops.attention import ulysses_attention

        mesh = make_mesh({"data": 8, "sequence": 1})
        q, k, v = _qkv(b=2, t=16, h=2, d=8)
        ref = dot_product_attention(q, k, v, causal=True)
        out = ulysses_attention(q, k, v, mesh=mesh, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


# ------------------------------------------------- sliding window x SP


class TestWindowedSequenceParallel:
    """Sliding-window attention composed with sequence parallelism
    (VERDICT r04 item 3 — the former feature-matrix hole): ring masks its
    live hops to the band and NEVER ROTATES dead hops (the loop unrolls to
    the static ring_live_hops bound), ulysses applies the band as a local
    mask after its exchange. Both must equal the banded dense reference."""

    # windows spanning: degenerate (1), sub-hop (5), exactly one hop (8,9),
    # two hops (16), nearly full (31), band never binds (64 > T)
    WINDOWS = [1, 5, 8, 9, 16, 31, 64]

    @pytest.mark.parametrize("window", WINDOWS)
    def test_ring_matches_banded_dense(self, window):
        mesh = make_mesh({"sequence": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(b=2, t=32, h=2, d=8)
        ref = dot_product_attention(q, k, v, causal=True, window=window)
        out = ring_attention(q, k, v, mesh=mesh, causal=True, window=window)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    @pytest.mark.parametrize("window", [5, 16, 48])
    def test_ring_flash_matches_banded_dense(self, window):
        """Same band, through the Pallas kernel (static q_offset per hop,
        out-of-band tiles skipped in-kernel)."""
        mesh = make_mesh({"sequence": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(b=2, t=64, h=2, d=16)
        ref = dot_product_attention(q, k, v, causal=True, window=window)
        out = ring_attention(
            q, k, v, mesh=mesh, causal=True, window=window,
            use_flash=True, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    @pytest.mark.slow
    @pytest.mark.parametrize("use_flash", [False, True])
    def test_ring_gradients_match_banded_dense(self, use_flash):
        mesh = make_mesh({"sequence": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(b=2, t=64, h=2, d=16)
        window = 20

        def loss_dense(q, k, v):
            return jnp.sum(
                dot_product_attention(q, k, v, causal=True, window=window)
                ** 2
            )

        def loss_ring(q, k, v):
            return jnp.sum(
                ring_attention(
                    q, k, v, mesh=mesh, causal=True, window=window,
                    use_flash=use_flash, interpret=use_flash,
                )
                ** 2
            )

        ref = jax.grad(loss_dense, (0, 1, 2))(q, k, v)
        got = jax.grad(loss_ring, (0, 1, 2))(q, k, v)
        for a, b in zip(got, ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    def test_ring_window_composes_with_gqa(self):
        """kv_groups (GQA rotation at kv-head size) x window: parity vs the
        banded dense reference on pre-broadcast K/V."""
        mesh = make_mesh({"sequence": 4}, devices=jax.devices()[:4])
        q, _, _ = _qkv(b=2, t=32, h=4, d=8, seed=1)
        _, k, v = _qkv(b=2, t=32, h=2, d=8, seed=2)
        kx = jnp.repeat(k, 2, axis=2)
        vx = jnp.repeat(v, 2, axis=2)
        ref = dot_product_attention(q, kx, vx, causal=True, window=10)
        out = ring_attention(
            q, k, v, mesh=mesh, causal=True, window=10, kv_groups=2
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    @pytest.mark.parametrize("window", WINDOWS)
    def test_ulysses_matches_banded_dense(self, window):
        from distributed_pytorch_tpu.ops.attention import ulysses_attention

        mesh = make_mesh({"sequence": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(b=2, t=32, h=4, d=8)
        ref = dot_product_attention(q, k, v, causal=True, window=window)
        out = ulysses_attention(
            q, k, v, mesh=mesh, causal=True, window=window
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_ulysses_window_gradients(self):
        from distributed_pytorch_tpu.ops.attention import ulysses_attention

        mesh = make_mesh({"sequence": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(b=2, t=32, h=4, d=8)

        def loss_dense(q, k, v):
            return jnp.sum(
                dot_product_attention(q, k, v, causal=True, window=7) ** 2
            )

        def loss_uly(q, k, v):
            return jnp.sum(
                ulysses_attention(
                    q, k, v, mesh=mesh, causal=True, window=7
                )
                ** 2
            )

        ref = jax.grad(loss_dense, (0, 1, 2))(q, k, v)
        got = jax.grad(loss_uly, (0, 1, 2))(q, k, v)
        for a, b in zip(got, ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    def test_window_requires_causal(self):
        from distributed_pytorch_tpu.ops.attention import ulysses_attention

        mesh = make_mesh({"sequence": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(b=2, t=32, h=4, d=8)
        with pytest.raises(ValueError, match="causal"):
            ring_attention(q, k, v, mesh=mesh, causal=False, window=4)
        with pytest.raises(ValueError, match="causal"):
            ulysses_attention(q, k, v, mesh=mesh, causal=False, window=4)

    def test_ring_live_hops_bound(self):
        from distributed_pytorch_tpu.ops.attention import ring_live_hops

        assert ring_live_hops(8, 8, 1) == 0  # self-only band
        assert ring_live_hops(8, 8, 2) == 1
        assert ring_live_hops(8, 8, 8) == 1
        assert ring_live_hops(8, 8, 9) == 1  # hop 2's newest key: gap 9
        assert ring_live_hops(8, 8, 10) == 2
        assert ring_live_hops(4, 8, 10**6) == 3  # capped at axis_size - 1

    def test_dead_hops_are_not_rotated(self):
        """The O(window) ICI claim, verified on the lowered program: with
        W <= t_local + 1 only ONE hop (2 collective-permutes: k and v)
        survives; with W = 1 the program has NO collective-permute at
        all. The unwindowed causal ring keeps its rotating while-loop."""
        mesh = make_mesh({"sequence": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(b=2, t=32, h=2, d=8)  # t_local = 8

        def lowered(window):
            fn = lambda q, k, v: ring_attention(  # noqa: E731
                q, k, v, mesh=mesh, causal=True, window=window
            )
            return jax.jit(fn).lower(q, k, v).as_text()

        assert lowered(1).count("collective_permute") == 0
        assert lowered(5).count("collective_permute") == 2
        assert lowered(10).count("collective_permute") == 4
