"""Fleet-wide distributed tracing: merge alignment, waterfall partition,
head+tail sampling, pruning, and the snapshot carry of ``trace_id``.

Two layers of coverage. The pure tests drive ``obs.disttrace`` with
synthetic trace documents — epoch alignment, pid remapping, the exact
waterfall partition, sampler determinism and bounded memory — without
touching an engine. The integration tests push seeded Poisson-ish load
through a traced ``FrontDoor`` over a real engine and assert the property
the module is built around: every trace's waterfall components sum to its
end-to-end latency (the partition is exact by construction; 5% is float
slack). All on CPU (conftest pins JAX_PLATFORMS=cpu).
"""

import dataclasses
import json
import random

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu.obs import (
    WATERFALL_COMPONENTS,
    TraceSampler,
    Tracer,
    flow_id,
    format_waterfall,
    merge_traces,
    prune_trace,
    request_waterfall,
    trace_ids,
)
from distributed_pytorch_tpu.obs.tracer import _PID_DOOR, _PID_ROUTER
from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.serving import (
    FrontDoor,
    InferenceEngine,
    SamplingParams,
    TenantConfig,
)
from distributed_pytorch_tpu.serving.elastic import RequestSnapshot


# ----------------------------------------------------------- fixtures


def tiny_lm():
    return TransformerLM(
        vocab_size=48, d_model=16, n_layers=1, n_heads=2, d_ff=32,
        dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def model_and_params():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


ENGINE_KW = dict(
    max_slots=4, max_seq_len=32, page_size=4, token_budget=16,
    max_prefill_chunk=8, debug=True,
)
SP = SamplingParams(max_new_tokens=6)


def traced_door(model, params, sampler=None, **door_kw):
    eng = InferenceEngine(model, params, tracer=Tracer(), **ENGINE_KW)
    door = FrontDoor(
        eng,
        tenants={"anon": TenantConfig()},
        tracer=Tracer(),
        sampler=sampler,
        **door_kw,
    )
    return eng, door


# ------------------------------------------------------------- sampler


def test_sampler_head_draw_is_deterministic():
    """The head verdict is a pure function of (seed, trace_id): two
    sampler instances agree on every id, so any layer could consult its
    own copy and reach the door's decision."""
    a = TraceSampler(head_rate=0.5, seed=7)
    b = TraceSampler(head_rate=0.5, seed=7)
    ids = [f"d{i:06x}" for i in range(500)]
    assert [a.head_keep(t) for t in ids] == [b.head_keep(t) for t in ids]
    kept = sum(a.head_keep(t) for t in ids)
    assert 0.35 * len(ids) < kept < 0.65 * len(ids)
    assert not any(TraceSampler(head_rate=0.0).head_keep(t) for t in ids)
    assert all(TraceSampler(head_rate=1.0).head_keep(t) for t in ids)


def test_sampler_tail_keeps_override_head_drop():
    s = TraceSampler(head_rate=0.0)
    assert s.note_end("t-ok") is False
    assert s.note_end("t-failed", failed=True) is True
    assert s.note_end("t-moved", failed_over=True) is True
    assert s.note_end("t-slow", slo_violated=True) is True
    assert s.counters() == {
        "traces_ended": 4,
        "traces_kept_head": 0,
        "traces_kept_tail": 3,
        "traces_dropped": 1,
        "traces_evicted": 0,
    }
    assert s.kept_ids() == ["t-failed", "t-moved", "t-slow"]
    assert s.drain_drops() == {"t-ok"}
    assert s.drain_drops() == set()  # drained means drained


def test_sampler_kept_ring_is_bounded():
    s = TraceSampler(head_rate=0.0, max_kept=2)
    for i in range(4):
        s.note_end(f"t{i}", failed=True)
    assert s.kept_ids() == ["t2", "t3"]
    assert s.counters()["traces_evicted"] == 2
    # Evicted ids become pending drops — bounded memory means the spans
    # go too, not just the bookkeeping.
    assert s.drain_drops() == {"t0", "t1"}


def test_sampler_rejects_bad_config():
    with pytest.raises(ValueError):
        TraceSampler(head_rate=1.5)
    with pytest.raises(ValueError):
        TraceSampler(max_kept=0)


# --------------------------------------------------------------- merge


def _doc(epoch, events, pid_names=None):
    tev = []
    for pid, name in (pid_names or {}).items():
        tev.append({
            "ph": "M", "name": "process_name", "pid": pid,
            "args": {"name": name},
        })
    tev.extend(events)
    return {
        "traceEvents": tev,
        "displayTimeUnit": "ms",
        "metadata": {"wall_epoch_s": epoch},
    }


def test_merge_aligns_epochs_and_remaps_pids():
    """Each source's monotonic timeline is shifted by its wall-clock epoch
    delta; pids stride by source index so replica lanes never collide."""
    door = _doc(
        100.0,
        [{"ph": "b", "cat": "door", "id": 1, "ts": 0.0, "pid": _PID_DOOR,
          "name": "stream", "args": {"trace_id": "d000000"}}],
        pid_names={_PID_DOOR: "frontdoor"},
    )
    eng = _doc(
        100.5,  # booted half a second later
        [{"ph": "b", "cat": "request", "id": 7, "ts": 250.0, "pid": 2,
          "name": "req 7", "args": {"trace_id": "d000000"}}],
        pid_names={2: "requests", 5: "unused-lane"},
    )
    merged = merge_traces(door, eng, labels=["door", "r0"])
    assert merged["metadata"] == {
        "wall_epoch_s": 100.0, "sources": ["door", "r0"],
    }
    by_ph = {e["ph"]: e for e in merged["traceEvents"] if e["ph"] != "M"}
    assert by_ph["b"] is not None
    spans = [e for e in merged["traceEvents"] if e["ph"] == "b"]
    door_ev = next(e for e in spans if e["cat"] == "door")
    eng_ev = next(e for e in spans if e["cat"] == "request")
    assert door_ev["ts"] == 0.0 and door_ev["pid"] == _PID_DOOR
    # 0.5s epoch delta (500_000us) + its own 250us monotonic ts.
    assert eng_ev["ts"] == pytest.approx(500_250.0)
    assert eng_ev["pid"] == 10 + 2
    metas = [e for e in merged["traceEvents"] if e["ph"] == "M"]
    names = {e["pid"]: e["args"]["name"] for e in metas}
    assert names[_PID_DOOR] == "door: frontdoor"
    assert names[12] == "r0: requests"
    assert 15 not in names  # metadata for unused lanes is dropped
    json.loads(json.dumps(merged))  # still plain Chrome JSON


def test_merge_accepts_live_tracers_and_labels_them():
    a, b = Tracer(), Tracer()
    a.span_begin(_PID_DOOR, 0, "stream", trace_id="d000000")
    b.span_begin(_PID_ROUTER, 0, "route", trace_id="d000000")
    merged = merge_traces(a, b)
    assert merged["metadata"]["sources"] == ["door", "router"]
    assert trace_ids(merged) == ["d000000"]


def test_merge_empty_is_valid():
    merged = merge_traces()
    assert merged["traceEvents"] == []
    assert trace_ids(merged) == []


# ----------------------------------------------------------- waterfall


def test_waterfall_is_an_exact_partition_synthetic():
    """Handcrafted timeline with every transition: the components must
    sum to e2e exactly, and each interval must land in the right
    bucket."""
    us = 1e6
    events = [
        # Door: open at 0, admitted at 3s (1s of it token-bucket pacing).
        {"ph": "b", "cat": "door", "id": 0, "ts": 0.0, "pid": 3,
         "name": "stream", "args": {"trace_id": "T"}},
        {"ph": "n", "cat": "door", "id": 0, "ts": 3 * us, "pid": 3,
         "name": "admitted", "args": {"trace_id": "T", "pacing_s": 1.0}},
        # Engine: span opens at 4s (1s of route), slot admit 5s, first
        # token 6s, second token 7s.
        {"ph": "b", "cat": "request", "id": 7, "ts": 4 * us, "pid": 2,
         "name": "req 7", "args": {"trace_id": "T"}},
        {"ph": "n", "cat": "request", "id": 7, "ts": 5 * us, "pid": 2,
         "name": "admit", "args": {}},
        {"ph": "n", "cat": "request", "id": 7, "ts": 6 * us, "pid": 2,
         "name": "decode_token", "args": {}},
        {"ph": "n", "cat": "request", "id": 7, "ts": 7 * us, "pid": 2,
         "name": "decode_token", "args": {}},
        # Preempted at 7s, re-admitted and decoding again at 9s.
        {"ph": "n", "cat": "request", "id": 7, "ts": 7 * us, "pid": 2,
         "name": "preempt", "args": {}},
        {"ph": "n", "cat": "request", "id": 7, "ts": 9 * us, "pid": 2,
         "name": "decode_token", "args": {}},
        {"ph": "e", "cat": "request", "id": 7, "ts": 10 * us, "pid": 2,
         "name": "req 7", "args": {}},
        {"ph": "e", "cat": "door", "id": 0, "ts": 10 * us, "pid": 3,
         "name": "stream", "args": {"trace_id": "T"}},
    ]
    doc = {"traceEvents": events, "metadata": {"wall_epoch_s": 0.0}}
    wf = request_waterfall(doc, "T")
    comp = wf["components"]
    assert wf["e2e_s"] == pytest.approx(10.0)
    assert sum(comp.values()) == pytest.approx(wf["e2e_s"])
    assert comp["queue_wait"] == pytest.approx(3.0)  # 2 door + 1 engine
    assert comp["pacing"] == pytest.approx(1.0)
    assert comp["route"] == pytest.approx(1.0)
    assert comp["prefill"] == pytest.approx(1.0)
    assert comp["decode_active"] == pytest.approx(2.0)
    assert comp["preempt_rework"] == pytest.approx(2.0)
    assert set(comp) == set(WATERFALL_COMPONENTS)
    table = format_waterfall(wf)
    assert "trace T" in table and "preempt_rework" in table


def test_waterfall_unknown_trace_id_raises():
    with pytest.raises(KeyError):
        request_waterfall({"traceEvents": []}, "nope")


# ------------------------------------------------------------- pruning


def test_prune_trace_removes_spans_and_flows_keeps_context():
    tr = Tracer()
    tr.span_begin(_PID_DOOR, 0, "stream", trace_id="keep")
    tr.flow("s", "keep", _PID_DOOR)
    tr.span_end(_PID_DOOR, 0, "stream", trace_id="keep")
    tr.span_begin(_PID_DOOR, 1, "stream", trace_id="drop")
    tr.flow("s", "drop", _PID_DOOR)
    tr.span_end(_PID_DOOR, 1, "stream", trace_id="drop")
    tr.instant("backpressure_stall", pid=_PID_DOOR, dur_s=0.1)
    opened, closed = tr.spans_opened, tr.spans_closed
    removed = prune_trace(tr, ["drop"])
    assert removed == 3  # b + e + flow arrow
    assert tr.spans_opened == opened - 1
    assert tr.spans_closed == closed - 1
    doc = tr.to_perfetto()
    assert trace_ids(doc) == ["keep"]
    assert not any(
        e.get("cat") == "flow" and e.get("id") == flow_id("drop")
        for e in doc["traceEvents"]
    )
    # Global context (the stall instant) survives pruning.
    assert any(
        e.get("name") == "backpressure_stall"
        for e in doc["traceEvents"]
    )
    assert prune_trace(tr, []) == 0


# ------------------------------------------- integration: door + engine


def drive_poisson(door, prompts, seed=1234):
    """Submit prompts on seeded geometric pump-round gaps (Poisson-ish,
    deterministic — no wall clock), pump to completion, return delivered
    token lists."""
    rng = random.Random(seed)
    schedule = {}
    rnd = 0
    for idx in range(len(prompts)):
        schedule.setdefault(rnd, []).append(idx)
        while rng.random() < 0.5:
            rnd += 1
    streams = [None] * len(prompts)
    rounds = 0
    while True:
        for idx in schedule.pop(rounds, []):
            streams[idx] = door.open_stream(prompts[idx], params=SP)
        if not schedule and all(
            s is not None and s.done for s in streams
        ):
            break
        door.pump()
        rounds += 1
        assert rounds < 2000, "poisson drive did not converge"
    return streams, [s.drain() for s in streams]


POISSON_PROMPTS = [
    [5, 7, 11, 2, t, t + 1] for t in (1, 9, 17, 25)
] + [[2, 2, 3], [6, 1, 9, 4, 4, 4, 4]]


def test_waterfall_sums_to_e2e_under_poisson_load(model_and_params):
    """The property the partition is built for, on real spans: every
    request admitted under staggered load decomposes into components that
    sum to its end-to-end latency within 5% (exact minus float slack)."""
    model, params = model_and_params
    eng, door = traced_door(
        model, params, sampler=TraceSampler(head_rate=1.0, max_kept=64)
    )
    try:
        streams, outs = drive_poisson(door, POISSON_PROMPTS)
        assert all(len(o) == SP.max_new_tokens for o in outs)
        merged = merge_traces(*door.trace_documents())
        ids = trace_ids(merged)
        assert len(ids) == len(POISSON_PROMPTS)
        assert ids == [s.trace_id for s in streams]  # minted in order
        for tid in ids:
            wf = request_waterfall(merged, tid)
            assert wf["e2e_s"] > 0
            total = sum(wf["components"].values())
            assert total == pytest.approx(wf["e2e_s"], rel=0.05), (
                f"{tid}: components {wf['components']} sum {total} "
                f"!= e2e {wf['e2e_s']}"
            )
            assert all(v >= 0 for v in wf["components"].values())
            # A completed request spent time computing somewhere.
            assert (
                wf["components"]["prefill"]
                + wf["components"]["decode_active"]
            ) > 0
        assert door.sampler.counters()["traces_ended"] == len(ids)
        assert door.sampler.kept_ids() == ids  # head_rate=1.0 keeps all
    finally:
        eng.close()


def test_flow_arrows_cross_door_to_engine(model_and_params):
    """The door mints the trace (flow 's'); the engine's request lane
    binds to it (flow 't') — that pair is what draws the arrow between
    process lanes in Perfetto."""
    model, params = model_and_params
    eng, door = traced_door(model, params)
    try:
        stream = door.open_stream(POISSON_PROMPTS[0], params=SP)
        door.drive()
        stream.drain()
        assert stream.trace_id == "d000000"  # door-minted, stable format
        merged = merge_traces(*door.trace_documents())
        flows = [
            (e["ph"], e["pid"])
            for e in merged["traceEvents"]
            if e.get("cat") == "flow"
            and e.get("args", {}).get("trace_id") == stream.trace_id
        ]
        phases = {ph for ph, _pid in flows}
        assert phases == {"s", "t"}, flows
        assert {pid for _ph, pid in flows if _ph == "s"} == {_PID_DOOR}
    finally:
        eng.close()


def test_head_drop_prunes_every_layer(model_and_params):
    """head_rate=0 with nothing failing: every trace is dropped at end,
    and the prune reaches both the door's tracer and the engine's —
    request/door spans vanish while the engine step timeline stays."""
    model, params = model_and_params
    eng, door = traced_door(
        model, params, sampler=TraceSampler(head_rate=0.0, max_kept=8)
    )
    try:
        _streams, outs = drive_poisson(door, POISSON_PROMPTS[:3])
        assert all(len(o) == SP.max_new_tokens for o in outs)
        counters = door.sampler.counters()
        assert counters["traces_dropped"] == 3
        assert counters["traces_kept_head"] == 0
        merged = merge_traces(*door.trace_documents())
        assert trace_ids(merged) == []
        # Dropping traces never drops the engine's own step timeline.
        assert any(
            e.get("ph") == "X" for e in merged["traceEvents"]
        ), "engine step slices should survive sampling"
    finally:
        eng.close()


# ------------------------------------------------- snapshot round-trip


def _snapshot(**over):
    base = dict(
        req_id=3, prompt=(5, 7, 11), generated=(1, 2), max_new_tokens=6,
        temperature=0.0, seed=0, stop_token=None, deadline_s=None,
        metadata=None, preempt_count=0, age_s=0.5, ttft_s=0.1,
        kv_committed=4, trie_keys=("abc",),
    )
    base.update(over)
    return RequestSnapshot(**base)


def test_request_snapshot_json_carries_trace_id():
    snap = _snapshot(trace_id="d00002a")
    entry = json.loads(json.dumps(dataclasses.asdict(snap)))
    entry["prompt"] = tuple(entry["prompt"])
    entry["generated"] = tuple(entry["generated"])
    entry["trie_keys"] = tuple(entry["trie_keys"])
    entry["host_keys"] = tuple(entry["host_keys"])
    entry["stop_sequences"] = tuple(
        tuple(s) for s in entry["stop_sequences"]
    )
    assert RequestSnapshot(**entry) == snap
    assert RequestSnapshot(**entry).trace_id == "d00002a"


def test_request_snapshot_json_backcompat_without_trace_id():
    """Snapshots written before distributed tracing have no trace_id key
    and must still decode (the field is defaulted-last on purpose)."""
    snap = _snapshot()
    entry = json.loads(json.dumps(dataclasses.asdict(snap)))
    entry.pop("trace_id")
    entry["prompt"] = tuple(entry["prompt"])
    entry["generated"] = tuple(entry["generated"])
    entry["trie_keys"] = tuple(entry["trie_keys"])
    entry["stop_sequences"] = tuple(
        tuple(s) for s in entry["stop_sequences"]
    )
    restored = RequestSnapshot(**entry)
    assert restored.trace_id is None
    assert restored.prompt == snap.prompt
