"""MetricLogger tests — the observability gap the reference leaves open
(SURVEY.md §5: loss computed but never logged, unused SummaryWriter import at
``multigpu_profile.py:10``)."""

import pytest
import json

from distributed_pytorch_tpu.metrics import MetricLogger


def parse_lines(text):
    records = []
    for line in text.splitlines():
        if line.startswith("{"):
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return records


def test_json_lines_schema(capsys):
    logger = MetricLogger()
    logger.log(3, loss=1.5, epoch=0)
    logger.log(4, eval_loss=0.25)
    logger.close()
    records = parse_lines(capsys.readouterr().out)
    assert len(records) == 2
    assert records[0]["step"] == 3 and records[0]["loss"] == 1.5
    assert records[1]["eval_loss"] == 0.25
    assert all("elapsed_s" in r for r in records)


def test_scalars_coerced_to_float(capsys):
    import numpy as np

    logger = MetricLogger()
    logger.log(np.int64(1), loss=np.float32(0.5))  # device/np scalars OK
    records = parse_lines(capsys.readouterr().out)
    assert records[0] == {
        "step": 1,
        "elapsed_s": records[0]["elapsed_s"],
        "loss": 0.5,
    }


@pytest.mark.slow
def test_tensorboard_scalars_written(tmp_path, capsys):
    import pytest

    pytest.importorskip(
        "torch.utils.tensorboard", reason="optional TB backend not installed"
    )
    logger = MetricLogger(tensorboard_dir=str(tmp_path))
    logger.log(0, loss=2.0)
    logger.log(1, loss=1.0)
    logger.close()
    capsys.readouterr()
    event_files = list(tmp_path.glob("events.out.tfevents.*"))
    assert event_files, "no TensorBoard event file written"
    assert event_files[0].stat().st_size > 0


def test_close_without_writer_is_safe():
    MetricLogger().close()
