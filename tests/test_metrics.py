"""MetricLogger tests — the observability gap the reference leaves open
(SURVEY.md §5: loss computed but never logged, unused SummaryWriter import at
``multigpu_profile.py:10``)."""

import pytest
import json

from distributed_pytorch_tpu.metrics import MetricLogger


def parse_lines(text):
    records = []
    for line in text.splitlines():
        if line.startswith("{"):
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return records


def test_json_lines_schema(capsys):
    logger = MetricLogger()
    logger.log(3, loss=1.5, epoch=0)
    logger.log(4, eval_loss=0.25)
    logger.close()
    records = parse_lines(capsys.readouterr().out)
    assert len(records) == 2
    assert records[0]["step"] == 3 and records[0]["loss"] == 1.5
    assert records[1]["eval_loss"] == 0.25
    assert all("elapsed_s" in r for r in records)


def test_scalars_coerced_to_float(capsys):
    import numpy as np

    logger = MetricLogger()
    logger.log(np.int64(1), loss=np.float32(0.5))  # device/np scalars OK
    records = parse_lines(capsys.readouterr().out)
    assert records[0] == {
        "step": 1,
        "elapsed_s": records[0]["elapsed_s"],
        "loss": 0.5,
    }


@pytest.mark.slow
def test_tensorboard_scalars_written(tmp_path, capsys):
    import pytest

    pytest.importorskip(
        "torch.utils.tensorboard", reason="optional TB backend not installed"
    )
    logger = MetricLogger(tensorboard_dir=str(tmp_path))
    logger.log(0, loss=2.0)
    logger.log(1, loss=1.0)
    logger.close()
    capsys.readouterr()
    event_files = list(tmp_path.glob("events.out.tfevents.*"))
    assert event_files, "no TensorBoard event file written"
    assert event_files[0].stat().st_size > 0


def test_close_without_writer_is_safe():
    MetricLogger().close()


# --------------------------------------------------- reservoir histograms


class TestReservoirHistogram:
    def _hist(self, capacity=8, seed=0):
        from distributed_pytorch_tpu.metrics import ReservoirHistogram

        return ReservoirHistogram(capacity, seed=seed)

    def test_exact_quantiles_before_overflow(self):
        h = self._hist(capacity=100)
        for v in range(1, 101):  # 1..100
            h.record(float(v))
        assert h.quantile(0.0) == 1.0
        assert h.quantile(1.0) == 100.0
        assert abs(h.quantile(0.5) - 50.5) < 1e-9
        assert h.count == 100
        assert h.sum == 5050.0

    def test_bounded_memory_and_exact_extremes(self):
        h = self._hist(capacity=16)
        for v in range(10_000):
            h.record(float(v))
        assert len(h._samples) == 16  # reservoir never grows past capacity
        # count/sum/min/max are exact regardless of sampling
        assert h.count == 10_000
        assert h.min == 0.0 and h.max == 9_999.0
        # sampled quantiles land in-range
        assert 0.0 <= h.quantile(0.5) <= 9_999.0

    def test_deterministic_per_seed(self):
        a, b = self._hist(seed=7), self._hist(seed=7)
        for v in range(1000):
            a.record(float(v % 37))
            b.record(float(v % 37))
        assert a.quantile(0.95) == b.quantile(0.95)
        assert sorted(a._samples) == sorted(b._samples)

    def test_empty_histogram(self):
        import math

        h = self._hist()
        assert h.count == 0
        assert math.isnan(h.quantile(0.5))
        s = h.summary("x_")
        assert s["x_count"] == 0

    def test_summary_keys_prefixed(self):
        h = self._hist()
        h.record(1.0)
        h.record(3.0)
        s = h.summary("step_time_s_")
        assert set(s) == {
            "step_time_s_count", "step_time_s_mean", "step_time_s_min",
            "step_time_s_max", "step_time_s_p50", "step_time_s_p95",
            "step_time_s_p99",
        }
        assert s["step_time_s_count"] == 2
        assert s["step_time_s_mean"] == 2.0


class TestReservoirGroup:
    def _group(self, **kw):
        from distributed_pytorch_tpu.metrics import ReservoirGroup

        kw.setdefault("capacity", 64)
        kw.setdefault("seed", 5)
        return ReservoirGroup(("hit", "miss"), **kw)

    def test_records_split_by_label(self):
        g = self._group()
        for v in (1.0, 2.0, 3.0):
            g.record("hit", v)
        g.record("miss", 10.0)
        assert g["hit"].count == 3
        assert g["miss"].count == 1
        assert g["miss"].mean == 10.0

    def test_unknown_label_rejected(self):
        g = self._group()
        with pytest.raises(KeyError):
            g.record("typo", 1.0)

    def test_summary_merges_prefixed_labels(self):
        g = self._group()
        g.record("hit", 2.0)
        s = g.summary("ttft_s_")
        assert s["ttft_s_hit_count"] == 1
        assert s["ttft_s_hit_p50"] == 2.0
        # unseen labels stay in the surface with count 0, not vanish
        assert s["ttft_s_miss_count"] == 0

    def test_labels_deterministic_and_independent(self):
        a, b = self._group(), self._group()
        for v in range(500):
            a.record("hit", float(v % 13))
            b.record("hit", float(v % 13))
            a.record("miss", float(v % 7))
            b.record("miss", float(v % 7))
        assert a["hit"].quantile(0.95) == b["hit"].quantile(0.95)
        assert a["miss"].quantile(0.95) == b["miss"].quantile(0.95)


# ------------------------------------------- state/merge (multi-host path)


class TestReservoirMerge:
    def _hist(self, capacity=64, seed=0):
        from distributed_pytorch_tpu.metrics import ReservoirHistogram

        return ReservoirHistogram(capacity, seed=seed)

    def test_state_json_round_trip(self):
        h = self._hist()
        for v in range(10):
            h.record(float(v))
        state = json.loads(json.dumps(h.state()))  # wire round-trip
        other = self._hist()
        other.merge_state(state)
        assert other.count == 10
        assert other.sum == h.sum
        assert other.min == 0.0 and other.max == 9.0
        assert sorted(other._samples) == sorted(h._samples)

    def test_merge_exact_aggregates_across_hosts(self):
        """count/sum/min/max fold exactly; percentiles come from the union
        of the sample streams (every sample retained while under capacity)."""
        a, b = self._hist(capacity=256, seed=1), self._hist(
            capacity=256, seed=2
        )
        for v in range(100):
            a.record(float(v))          # 0..99
        for v in range(100, 200):
            b.record(float(v))          # 100..199
        a.merge_state(b.state())
        assert a.count == 200
        assert a.sum == sum(float(v) for v in range(200))
        assert a.min == 0.0 and a.max == 199.0
        # Under capacity the merge is the exact union -> exact quantiles.
        assert abs(a.quantile(0.5) - 99.5) < 1e-9

    def test_merge_overflow_downsamples_to_capacity(self):
        a, b = self._hist(capacity=16, seed=3), self._hist(
            capacity=16, seed=4
        )
        for v in range(1000):
            a.record(float(v))
            b.record(float(v) + 1000.0)
        a.merge_state(b.state())
        assert len(a._samples) == 16
        assert a.count == 2000
        assert a.min == 0.0 and a.max == 1999.0
        assert 0.0 <= a.quantile(0.5) <= 1999.0

    def test_merge_empty_state_is_noop(self):
        h = self._hist()
        h.record(5.0)
        before = h.state()
        h.merge_state(self._hist().state())
        assert h.state() == before

    def test_merge_into_empty_adopts(self):
        import math

        empty, full = self._hist(), self._hist()
        for v in (1.0, 2.0, 3.0):
            full.record(v)
        empty.merge_state(full.state())
        assert empty.count == 3
        assert empty.quantile(0.5) == 2.0
        # and an empty-merged-with-empty reservoir still reports NaN
        # percentiles / count-0 summary, not a crash
        e2 = self._hist()
        e2.merge_state(self._hist().state())
        assert e2.count == 0
        assert math.isnan(e2.quantile(0.99))
        assert e2.summary("x_") == {"x_count": 0}

    def test_merge_deterministic(self):
        a1, a2 = self._hist(capacity=8, seed=9), self._hist(
            capacity=8, seed=9
        )
        src = self._hist(capacity=8, seed=1)
        for v in range(100):
            a1.record(float(v))
            a2.record(float(v))
            src.record(float(v) * 2.0)
        a1.merge_state(src.state())
        a2.merge_state(src.state())
        assert a1._samples == a2._samples

    def test_group_state_merge_round_trip(self):
        from distributed_pytorch_tpu.metrics import ReservoirGroup

        a = ReservoirGroup(("hit", "miss"), capacity=64, seed=5)
        b = ReservoirGroup(("hit", "miss"), capacity=64, seed=6)
        a.record("hit", 1.0)
        b.record("hit", 3.0)
        b.record("miss", 7.0)
        a.merge_state(json.loads(json.dumps(b.state())))
        assert a["hit"].count == 2
        assert a["hit"].quantile(0.5) == 2.0
        assert a["miss"].count == 1 and a["miss"].mean == 7.0

    def test_group_merge_unknown_label_rejected(self):
        from distributed_pytorch_tpu.metrics import ReservoirGroup

        a = ReservoirGroup(("hit", "miss"), capacity=8)
        b = ReservoirGroup(("hit", "typo"), capacity=8)
        with pytest.raises(KeyError):
            a.merge_state(b.state())
