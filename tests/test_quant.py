"""Weight-only int8 quantization: numerics, tree mapping, decode parity."""

import pytest
import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import optax

from distributed_pytorch_tpu.models import TransformerLM
from distributed_pytorch_tpu.ops.quant import (
    QuantTensor,
    TRANSFORMER_QUANT_RULES,
    dequantize,
    dequantize_pytree,
    quantize_int8,
    quantize_pytree,
    quantized_bytes,
)


def tiny_lm(**kw):
    return TransformerLM(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64, **kw
    )


def lm_params(model=None, seed=0):
    model = model or tiny_lm()
    tokens = jnp.zeros((2, 16), jnp.int32)
    return model.init(jax.random.PRNGKey(seed), tokens)["params"]


def trained_tiny_lm(steps=30):
    """Tiny LM trained on a repeating pattern so logits carry real margins
    (random-init params have near-tie argmax that quantization noise flips).
    Returns (model, params, the training sequences)."""
    from distributed_pytorch_tpu.training.losses import (
        softmax_cross_entropy_loss,
    )
    from distributed_pytorch_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )

    model = tiny_lm()
    seq = np.tile(np.arange(16, dtype=np.int32), (8, 2))  # [8, 32]
    inputs, targets = seq[:, :-1], seq[:, 1:]
    state = create_train_state(model, optax.adam(1e-2), inputs)
    step = make_train_step(
        model.apply, optax.adam(1e-2), softmax_cross_entropy_loss
    )
    for _ in range(steps):
        state, _ = step(state, (jnp.asarray(inputs), jnp.asarray(targets)))
    return model, state.params, seq


class TestQuantizeInt8:
    def test_roundtrip_error_bound(self):
        rng = np.random.default_rng(0)
        w = (rng.standard_normal((256, 128)) * 0.05).astype(np.float32)
        qt = quantize_int8(jnp.asarray(w), (0,))
        assert qt.q.dtype == jnp.int8
        assert qt.scale.shape == (1, 128)
        back = np.asarray(dequantize(qt, jnp.float32))
        rel_rms = np.sqrt(np.mean((back - w) ** 2)) / np.sqrt(np.mean(w**2))
        assert rel_rms < 0.01

    def test_per_channel_scales_are_independent(self):
        # One huge column must not blow up the quantization of the others.
        w = np.full((64, 4), 0.01, np.float32)
        w[:, 3] = 100.0
        qt = quantize_int8(jnp.asarray(w), (0,))
        back = np.asarray(dequantize(qt, jnp.float32))
        np.testing.assert_allclose(back[:, 0], w[:, 0], rtol=0.01)
        np.testing.assert_allclose(back[:, 3], w[:, 3], rtol=0.01)

    def test_zero_channel_safe(self):
        w = np.zeros((16, 3), np.float32)
        qt = quantize_int8(jnp.asarray(w), (0,))
        assert np.all(np.isfinite(np.asarray(qt.scale)))
        np.testing.assert_array_equal(np.asarray(dequantize(qt)), 0)

    def test_3d_contract_dims(self):
        rng = np.random.default_rng(1)
        w = (rng.standard_normal((32, 4, 8)) * 0.1).astype(np.float32)
        qt = quantize_int8(jnp.asarray(w), (0,))  # QKV-style [d_model, H, Dh]
        assert qt.scale.shape == (1, 4, 8)
        qt2 = quantize_int8(jnp.asarray(w), (0, 1))  # out-style contraction
        assert qt2.scale.shape == (1, 1, 8)


class TestQuantizePytree:
    @pytest.mark.slow
    def test_rules_match_matmul_kernels_only(self):
        params = lm_params()
        qtree = quantize_pytree(params, TRANSFORMER_QUANT_RULES)
        flat = jtu.tree_flatten_with_path(
            qtree, is_leaf=lambda x: isinstance(x, QuantTensor)
        )[0]
        quantized_paths = {
            "/".join(str(getattr(e, "key", e)) for e in path)
            for path, leaf in flat
            if isinstance(leaf, QuantTensor)
        }
        assert any("attention/query/kernel" in p for p in quantized_paths)
        assert any("mlp/up/kernel" in p for p in quantized_paths)
        assert any("lm_head/kernel" in p for p in quantized_paths)
        # Embedding, biases and LayerNorm params pass through untouched.
        assert not any("embed" in p for p in quantized_paths)
        assert not any("bias" in p for p in quantized_paths)
        assert not any("ln_" in p for p in quantized_paths)

    @pytest.mark.slow
    def test_dequantize_pytree_restores_structure_and_values(self):
        params = lm_params()
        qtree = quantize_pytree(params)
        back = dequantize_pytree(qtree, jnp.float32)
        assert jtu.tree_structure(back) == jtu.tree_structure(params)
        for (path, a), (_, b) in zip(
            jtu.tree_flatten_with_path(params)[0],
            jtu.tree_flatten_with_path(back)[0],
        ):
            a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
            denom = np.sqrt(np.mean(a**2)) or 1.0
            assert np.sqrt(np.mean((a - b) ** 2)) / denom < 0.01, path

    def test_memory_reduction(self):
        qtree = quantize_pytree(lm_params())
        q_bytes, orig = quantized_bytes(qtree)
        assert q_bytes < 0.3 * orig  # ~4x minus the scale overhead


class TestQuantizedDecodeParity:
    @pytest.mark.slow
    def test_greedy_decode_matches_f32(self):
        """Weight-only int8 on a trained-ish model: greedy continuations must
        match the full-precision path token for token (quant noise ~0.3% RMS
        is far below typical logit margins on a structured task)."""
        from distributed_pytorch_tpu.generation import generate

        model, params, seq = trained_tiny_lm()
        prompt = jnp.asarray(seq[:2, :8], jnp.int32)
        full = generate(model, params, prompt, 12)
        quant = generate(model, params, prompt, 12, quantize=True)
        np.testing.assert_array_equal(np.asarray(full), np.asarray(quant))

    def test_prequantized_tree_accepted(self):
        from distributed_pytorch_tpu.generation import generate

        model = tiny_lm()
        params = lm_params(model)
        prompt = jnp.asarray(
            np.random.default_rng(3).integers(0, 64, (2, 6)), jnp.int32
        )
        fresh = generate(model, params, prompt, 5, quantize=True)
        pre = generate(
            model, quantize_pytree(params), prompt, 5, quantize=True
        )
        np.testing.assert_array_equal(np.asarray(fresh), np.asarray(pre))

    @pytest.mark.slow
    def test_quantized_tensor_parallel_decode_parity(self):
        """int8 decode composes with megatron TP shardings: the int8 kernels
        keep the kernel's placement, the per-channel scales drop the
        contracted axes, and the tokens match the unquantized single-device
        run of the same quantized weights."""
        from jax.sharding import NamedSharding
        from distributed_pytorch_tpu.generation import generate
        from distributed_pytorch_tpu.parallel.mesh import make_mesh
        from distributed_pytorch_tpu.parallel.partitioning import (
            TRANSFORMER_TP_RULES,
            make_param_specs,
        )

        model = tiny_lm()
        params = lm_params(model)
        prompt = jnp.asarray(
            np.random.default_rng(11).integers(0, 64, (4, 5)), jnp.int32
        )
        single = generate(model, params, prompt, 6, quantize=True)

        mesh = make_mesh({"data": 4, "tensor": 2})
        specs = make_param_specs(params, TRANSFORMER_TP_RULES, mesh=mesh)
        shardings = jtu.tree_map(lambda s: NamedSharding(mesh, s), specs)
        sharded = generate(
            model,
            params,
            prompt,
            6,
            quantize=True,
            mesh=mesh,
            param_shardings=shardings,
        )
        np.testing.assert_array_equal(np.asarray(sharded), np.asarray(single))


class TestQuantizedKVCache:
    @pytest.mark.slow
    def test_int8_cache_greedy_parity(self):
        """Per-(token, head) int8 KV cache: greedy continuations on a trained
        model match the bf16-cache path token for token."""
        from distributed_pytorch_tpu.generation import generate

        model, params, seq = trained_tiny_lm()
        prompt = jnp.asarray(seq[:2, :8], jnp.int32)
        full = generate(model, params, prompt, 12)
        q = generate(model, params, prompt, 12, quantized_cache=True)
        np.testing.assert_array_equal(np.asarray(full), np.asarray(q))

    def test_cache_buffers_are_int8(self):
        model = tiny_lm().clone(decode=True, quantized_cache=True)
        cache = model.init(
            jax.random.PRNGKey(0), jnp.zeros((2, 12), jnp.int32)
        )["cache"]
        flat = jtu.tree_flatten_with_path(cache)[0]
        kinds = {
            "/".join(str(getattr(e, "key", e)) for e in path): leaf
            for path, leaf in flat
        }
        k = next(v for p, v in kinds.items() if p.endswith("cached_key"))
        s = next(v for p, v in kinds.items() if p.endswith("key_scale"))
        assert k.dtype == jnp.int8 and k.shape == (2, 12, 4, 8)
        assert s.dtype == jnp.float32 and s.shape == (2, 12, 4)

    @pytest.mark.slow
    def test_composes_with_weight_quant_and_mesh(self):
        from distributed_pytorch_tpu.generation import generate
        from distributed_pytorch_tpu.parallel.mesh import make_mesh

        model, params, seq = trained_tiny_lm()
        prompt = jnp.asarray(seq[:8, :8], jnp.int32)
        single = generate(
            model, params, prompt, 8, quantize=True, quantized_cache=True
        )
        mesh = make_mesh({"data": 8})
        sharded = generate(
            model, params, prompt, 8, quantize=True, quantized_cache=True,
            mesh=mesh,
        )
        np.testing.assert_array_equal(np.asarray(single), np.asarray(sharded))


class TestDecodeByteAccounting:
    """Structural proof (no hardware needed): XLA's own cost analysis of
    the compiled decode program shows the int8 KV cache reads fewer bytes —
    a storage-level saving, so it holds on every backend. (The WEIGHT-quant
    traffic saving is fusion-dependent — the CPU backend materializes the
    dequantized weights instead of fusing the convert into the dot — so its
    verification is the on-chip A/B in tools/decode_bench.py, not a CPU
    byte count.) The fori_loop body is counted once, so this is per-step
    traffic."""

    @staticmethod
    def _body_bytes(model, params, batch, total_len):
        from distributed_pytorch_tpu.generation import _compiled_run

        decode = model.clone(decode=True)
        abstract = jax.eval_shape(
            decode.init,
            jax.random.PRNGKey(0),
            jnp.zeros((batch, total_len), jnp.int32),
        )["cache"]
        cache = jtu.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), abstract)
        tokens = jnp.zeros((batch, total_len), jnp.int32)
        lengths = jnp.full((batch,), 4, jnp.int32)
        rng = jax.random.PRNGKey(0)
        run = _compiled_run(decode, total_len, 0.0, 0)
        analysis = run.lower(
            params, tokens, cache, lengths, rng
        ).compile().cost_analysis()
        if isinstance(analysis, list):
            analysis = analysis[0]
        return float(analysis["bytes accessed"])

    @pytest.mark.slow
    def test_int8_cache_cuts_program_bytes(self):
        # The cache dominates this shape (tiny model, B=4, T=256 -> ~2 MB of
        # bf16 KV cache vs ~100 KB of weights).
        kw = dict(
            vocab_size=128, d_model=32, n_layers=2, n_heads=4, d_ff=64,
            dtype=jnp.bfloat16,
        )
        params = TransformerLM(**kw).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        bf16 = jtu.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            params,
        )
        full = self._body_bytes(
            TransformerLM(**kw), bf16, batch=4, total_len=256
        )
        quant = self._body_bytes(
            TransformerLM(**kw, quantized_cache=True), bf16, batch=4,
            total_len=256,
        )
        assert quant < 0.75 * full, (quant, full)


class TestQuantMatmulKernel:
    """Pallas int8-weight matmul: the kernel's VMEM dequant must match the
    XLA dequant + matmul reference (interpret mode runs the real kernel
    logic on CPU)."""

    def _case(self, b, k, n, block_n=128, seed=0):
        from distributed_pytorch_tpu.ops.quant_matmul import quant_matmul

        rng = np.random.default_rng(seed)
        x = jnp.asarray(
            rng.standard_normal((b, k)) * 0.5, jnp.float32
        )
        w = (rng.standard_normal((k, n)) * 0.05).astype(np.float32)
        qt = quantize_int8(jnp.asarray(w), (0,))
        ref = x @ dequantize(qt, jnp.float32)
        out = quant_matmul(x, qt, block_n=block_n, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_matches_dequant_reference(self):
        self._case(b=8, k=256, n=256)

    def test_row_padding(self):
        self._case(b=3, k=128, n=256)  # B below the f32 sublane multiple

    def test_multi_block(self):
        self._case(b=8, k=128, n=512, block_n=128)

    def test_fallback_on_indivisible_n(self):
        from distributed_pytorch_tpu.ops.quant_matmul import quant_matmul

        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((4, 64)), jnp.float32)
        w = (rng.standard_normal((64, 96)) * 0.1).astype(np.float32)
        qt = quantize_int8(jnp.asarray(w), (0,))
        out = quant_matmul(x, qt, block_n=512)  # 96 % 512 != 0 -> XLA path
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(x @ dequantize(qt, jnp.float32)),
            rtol=1e-5,
        )

    def test_rejects_wrong_quant_layout(self):
        import pytest as _pytest

        from distributed_pytorch_tpu.ops.quant_matmul import quant_matmul

        w = jnp.ones((8, 4, 4), jnp.float32)
        qt = quantize_int8(w, (0,))
        with _pytest.raises(ValueError, match="2-D"):
            quant_matmul(jnp.ones((2, 8), jnp.float32), qt)


class TestMoEQuantCoverage:
    """Round-3 ADVICE: MoE expert kernels are the bulk of an MoE model's
    params — the rules must cover them, and generate(quantize=True) must
    report, not hide, poor rule coverage."""

    def _moe_params(self):
        model = TransformerLM(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
            n_experts=4, moe_every=1,
        )
        tokens = jnp.zeros((2, 16), jnp.int32)
        return model.init(jax.random.PRNGKey(0), tokens)["params"]

    @pytest.mark.slow
    def test_expert_kernels_quantized(self):
        from distributed_pytorch_tpu.ops.quant import quant_coverage

        params = self._moe_params()
        qtree = quantize_pytree(params, TRANSFORMER_QUANT_RULES)
        flat = jtu.tree_flatten_with_path(
            qtree, is_leaf=lambda x: isinstance(x, QuantTensor)
        )[0]
        quantized_paths = {
            "/".join(str(getattr(e, "key", e)) for e in path)
            for path, leaf in flat
            if isinstance(leaf, QuantTensor)
        }
        assert any("moe/up_kernel" in p for p in quantized_paths)
        assert any("moe/down_kernel" in p for p in quantized_paths)
        # The float32-softmax router stays full precision.
        assert not any("router" in p for p in quantized_paths)
        # With experts covered, the matched fraction is the bulk of params.
        assert quant_coverage(qtree) > 0.5

    def test_expert_quant_numerics(self):
        params = self._moe_params()
        qtree = quantize_pytree(params, TRANSFORMER_QUANT_RULES)
        back = dequantize_pytree(qtree, jnp.float32)
        for (path, a), (_, b) in zip(
            jtu.tree_flatten_with_path(params)[0],
            jtu.tree_flatten_with_path(back)[0],
        ):
            a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
            denom = np.sqrt(np.mean(a**2)) or 1.0
            assert np.sqrt(np.mean((a - b) ** 2)) / denom < 0.01, path

    def test_coverage_warning_on_unmatched_tree(self):
        import warnings

        from distributed_pytorch_tpu.generation import generate

        model = tiny_lm()
        # A param tree whose paths the rules cannot match (as if from a
        # model family the rule table doesn't know).
        foreign = {"encoder": {"w_in": jnp.ones((32, 64), jnp.float32)}}
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            try:
                generate(
                    model,
                    foreign,
                    jnp.zeros((1, 4), jnp.int32),
                    1,
                    quantize=True,
                )
            except Exception:
                pass  # apply fails on the foreign tree; the warning fires first
        assert any("matched only" in str(w.message) for w in caught)


class TestQuantMatmulKTiling:
    """K is tiled (grid dim 1) with in-place accumulation; shapes no tile
    divides fall back to the XLA path (round-3 ADVICE: whole-K-in-VMEM)."""

    def _ref_and_out(self, b, k, n, **kw):
        from distributed_pytorch_tpu.ops.quant_matmul import quant_matmul

        rng = np.random.default_rng(7)
        x = jnp.asarray(rng.standard_normal((b, k)) * 0.5, jnp.float32)
        w = (rng.standard_normal((k, n)) * 0.05).astype(np.float32)
        qt = quantize_int8(jnp.asarray(w), (0,))
        ref = x @ dequantize(qt, jnp.float32)
        out = quant_matmul(x, qt, interpret=True, **kw)
        return np.asarray(ref), np.asarray(out)

    def test_multiple_k_tiles(self):
        # 384 = 3 x 128: smallest candidate divides, so 3 accumulation steps.
        ref, out = self._ref_and_out(b=4, k=384, n=512, block_n=128)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_large_k_tile_selection(self):
        # 2048 divides: single biggest tile; exercises candidate ordering.
        ref, out = self._ref_and_out(b=2, k=2048, n=128, block_n=128)
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)

    def test_fallback_on_unaligned_k(self):
        from distributed_pytorch_tpu.ops.quant_matmul import quant_matmul

        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal((4, 100)), jnp.float32)
        w = (rng.standard_normal((100, 128)) * 0.1).astype(np.float32)
        qt = quantize_int8(jnp.asarray(w), (0,))
        out = quant_matmul(x, qt, block_n=128)  # 100 has no 128-mult tile
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(x @ dequantize(qt, jnp.float32)),
            rtol=1e-5,
        )
