"""Elastic launcher (tpurun) + native rendezvous store tests.

Covers the torchrun-equivalent layer the reference outsources
(SURVEY.md §3.3): env-var contract, rendezvous via the C++ TCP store,
failure detection, and restart-the-world recovery with TPURUN_RESTART_COUNT.

Most workers here are tiny pure-Python scripts (no jax import) so the tests
run in seconds; ``TestElasticTraining`` at the bottom runs the real thing —
live JAX workers of ``examples/multihost_pod.py`` under tpurun, one of them
SIGKILLed mid-epoch. Clean-relaunch snapshot resume (no agent in the loop) is
covered in ``tests/test_multiprocess.py``.
"""

import os
import signal
import socket
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def epoch_losses(text):
    """epoch -> epoch_loss parsed from a worker's metrics JSON lines."""
    import json

    losses = {}
    for line in text.splitlines():
        if line.startswith("{"):
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "epoch_loss" in record:
                losses[int(record["epoch"])] = record["epoch_loss"]
    return losses


# ----------------------------------------------------------------- KV store


class TestKVStore:
    @pytest.fixture()
    def store(self):
        from distributed_pytorch_tpu.elastic.store import KVStoreClient, KVStoreServer

        port = free_port()
        with KVStoreServer(port):
            with KVStoreClient("127.0.0.1", port) as client:
                yield client, port

    def test_set_get_roundtrip_with_spaces(self, store):
        client, _ = store
        client.set("a/key", "value with spaces + specials%")
        assert client.get("a/key") == "value with spaces + specials%"
        assert client.get("missing") is None

    def test_atomic_add(self, store):
        client, _ = store
        assert client.add("ctr", 2) == 2
        assert client.add("ctr", 3) == 5

    def test_wait_ge_blocks_until_target(self, store):
        from distributed_pytorch_tpu.elastic.store import KVStoreClient

        client, port = store
        assert client.wait_ge("joined", 2, timeout=0.2) is None  # times out

        def join_later():
            time.sleep(0.2)
            with KVStoreClient("127.0.0.1", port) as c2:
                c2.add("joined", 1)
                c2.add("joined", 1)

        threading.Thread(target=join_later).start()
        assert client.wait_ge("joined", 2, timeout=10) == 2

    def test_concurrent_adds_from_many_clients(self, store):
        """The rendezvous join-count must be exact under concurrency."""
        from distributed_pytorch_tpu.elastic.store import KVStoreClient

        client, port = store
        n_clients, n_adds = 8, 25

        def hammer():
            with KVStoreClient("127.0.0.1", port) as c:
                for _ in range(n_adds):
                    c.add("hammer", 1)

        threads = [threading.Thread(target=hammer) for _ in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert client.get("hammer") == str(n_clients * n_adds)

    def test_keys_prefix(self, store):
        client, _ = store
        client.set("hb/0", "x")
        client.set("hb/1", "y")
        client.set("other", "z")
        assert sorted(client.keys("hb/")) == ["hb/0", "hb/1"]


# ----------------------------------------------------------------- agent


def run_tpurun(
    tmp_path, worker_src: str, *args: str, timeout: float = 120, extra_env=None
):
    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent(worker_src))
    env = dict(os.environ, PYTHONPATH=REPO)
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "distributed_pytorch_tpu.elastic", *args, str(worker)],
        env=env,
        cwd=tmp_path,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


class TestElasticAgent:
    def test_standalone_env_contract(self, tmp_path):
        """Workers see the full torchrun-style env (SURVEY §2: ddp_setup env form)."""
        result = run_tpurun(
            tmp_path,
            """
            import os
            pid = os.environ["PROCESS_ID"]
            assert os.environ["NUM_PROCESSES"] == "3"
            assert os.environ["LOCAL_RANK"] == pid  # single node: local == global
            assert os.environ["TPURUN_RESTART_COUNT"] == "0"
            assert ":" in os.environ["COORDINATOR_ADDRESS"]
            open(f"saw.{pid}", "w").write("ok")
            """,
            "--standalone",
            "--nproc-per-node",
            "3",
        )
        assert result.returncode == 0, result.stderr
        assert sorted(p.name for p in tmp_path.glob("saw.*")) == [
            "saw.0",
            "saw.1",
            "saw.2",
        ]

    def test_explicit_jax_coordinator_port(self, tmp_path):
        """--jax-coordinator-port lands verbatim in COORDINATOR_ADDRESS (the
        round-2 'silent rdzv_port + 1 grab' is now an explicit, checkable
        flag)."""
        result = run_tpurun(
            tmp_path,
            """
            import os
            assert os.environ["COORDINATOR_ADDRESS"].endswith(":29777"), \
                os.environ["COORDINATOR_ADDRESS"]
            open("port_ok", "w").write("ok")
            """,
            "--standalone",
            "--nproc-per-node",
            "1",
            "--jax-coordinator-port",
            "29777",
        )
        assert result.returncode == 0, result.stderr
        assert (tmp_path / "port_ok").exists()

    def test_restart_on_worker_failure(self, tmp_path):
        """One worker fails at generation 0; the whole world restarts and
        succeeds at generation 1 (torchrun restart-all semantics)."""
        result = run_tpurun(
            tmp_path,
            """
            import os, sys
            restart = int(os.environ["TPURUN_RESTART_COUNT"])
            pid = os.environ["PROCESS_ID"]
            if restart == 0 and pid == "1":
                sys.exit(7)
            open(f"done.{pid}.{restart}", "w").write("ok")
            """,
            "--standalone",
            "--nproc-per-node",
            "2",
            "--max-restarts",
            "2",
        )
        assert result.returncode == 0, result.stderr
        # Generation 1 ran both workers; worker 0's gen-0 file may or may not
        # survive the kill, but both gen-1 files must exist.
        names = {p.name for p in tmp_path.glob("done.*")}
        assert {"done.0.1", "done.1.1"} <= names

    @pytest.mark.slow
    def test_hung_worker_detected_via_heartbeat_file(self, tmp_path):
        """A worker that stays ALIVE but stops making progress (wedged in a
        collective, SIGSTOPped, deadlocked) is invisible to exit-code polling;
        with --worker-heartbeat-timeout the agent watches each worker's
        TPURUN_HEARTBEAT_FILE and restarts the world when one goes stale."""
        result = run_tpurun(
            tmp_path,
            """
            import os, sys, time
            hb = os.environ["TPURUN_HEARTBEAT_FILE"]
            restart = int(os.environ["TPURUN_RESTART_COUNT"])
            pid = os.environ["PROCESS_ID"]

            def touch():
                open(hb, "w").write("x")

            if restart == 0:
                if pid == "1":
                    for _ in range(3):
                        touch()
                        time.sleep(0.5)
                    time.sleep(120)  # hang: alive but silent
                else:
                    for _ in range(240):  # healthy: keeps beating
                        touch()
                        time.sleep(0.5)
                    sys.exit(1)
            open(f"done.{pid}.{restart}", "w").write("ok")
            """,
            "--standalone",
            "--nproc-per-node",
            "2",
            "--max-restarts",
            "2",
            "--worker-heartbeat-timeout",
            "4",
        )
        assert result.returncode == 0, result.stdout + result.stderr
        assert "hung" in result.stdout
        names = {p.name for p in tmp_path.glob("done.*")}
        assert {"done.0.1", "done.1.1"} <= names

    def test_restarts_exhausted_is_fatal(self, tmp_path):
        result = run_tpurun(
            tmp_path,
            """
            import sys
            sys.exit(3)  # always fails
            """,
            "--standalone",
            "--nproc-per-node",
            "1",
            "--max-restarts",
            "1",
        )
        assert result.returncode == 1
        assert "giving up" in result.stderr

    @pytest.mark.slow
    def test_two_node_rendezvous(self, tmp_path):
        """Two agents on one machine = the sbatch_run.sh multinode shape."""
        port = free_port()
        worker = tmp_path / "worker.py"
        worker.write_text(
            textwrap.dedent(
                """
                import os
                pid = os.environ["PROCESS_ID"]
                assert os.environ["NUM_PROCESSES"] == "4"
                open(f"n.{pid}", "w").write(os.environ["LOCAL_RANK"])
                """
            )
        )
        env = dict(os.environ, PYTHONPATH=REPO)

        def launch(node_rank):
            return subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "distributed_pytorch_tpu.elastic",
                    "--nnodes",
                    "2",
                    "--node-rank",
                    str(node_rank),
                    "--nproc-per-node",
                    "2",
                    "--rdzv-endpoint",
                    f"127.0.0.1:{port}",
                    str(worker),
                ],
                env=env,
                cwd=tmp_path,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )

        agents = [launch(0), launch(1)]
        for a in agents:
            out, err = a.communicate(timeout=120)
            assert a.returncode == 0, err
        assert sorted(p.name for p in tmp_path.glob("n.*")) == [
            "n.0",
            "n.1",
            "n.2",
            "n.3",
        ]
        # LOCAL_RANK is per-node: global 0,1 -> node0 local 0,1; global 2,3 -> node1.
        assert (tmp_path / "n.2").read_text() == "0"
        assert (tmp_path / "n.3").read_text() == "1"


def test_parse_nnodes_forms():
    from distributed_pytorch_tpu.elastic.agent import _parse_nnodes

    assert _parse_nnodes("4") == (4, 4)
    assert _parse_nnodes("1:4") == (1, 4)
    assert _parse_nnodes(2) == (2, 2)
    for bad in ("0:2", "3:2", "0"):
        with pytest.raises(ValueError):
            _parse_nnodes(bad)


@pytest.mark.slow
class TestScaleDown:
    """--nnodes MIN:MAX (torchrun elastic form): a 2-agent world loses one
    node PERMANENTLY; the survivor's next rendezvous waits the scale-down
    grace, re-forms the world at size 1, and training completes with every
    sample still covered exactly once per completed epoch (the loader
    re-shards from the new NUM_PROCESSES)."""

    WORKER = """
    import json, os, sys, time

    pid = int(os.environ["PROCESS_ID"])
    W = int(os.environ["NUM_PROCESSES"])
    N, EPOCHS = 16, 3

    start = 0
    if os.path.exists("state.json"):
        start = json.load(open("state.json"))["epochs_done"]

    for epoch in range(start, EPOCHS):
        open(f"start.{epoch}.{pid}.w{W}", "w").write("")
        time.sleep(1.5)  # the kill window: mid-epoch work
        idx = list(range(pid, N, W))  # DistributedSampler-style stride shard
        with open(f"cov.{epoch}.{pid}.w{W}", "w") as f:
            json.dump(idx, f)
        # Filesystem stand-in for the end-of-epoch collective: an epoch only
        # counts as done when EVERY rank of this world contributed — exactly
        # like a real SPMD step, which cannot complete on a half-dead world.
        deadline = time.time() + 60
        while not all(
            os.path.exists(f"cov.{epoch}.{r}.w{W}") for r in range(W)
        ):
            if time.time() > deadline:
                sys.exit(9)
            time.sleep(0.1)
        if pid == 0:
            open(f"done.{epoch}.w{W}", "w").write("")
            with open("state.json.tmp", "w") as f:
                json.dump({"epochs_done": epoch + 1}, f)
            os.replace("state.json.tmp", "state.json")
        time.sleep(0.2)  # barrier slack before the next epoch
    """

    def test_world_reforms_smaller_with_full_coverage(self, tmp_path):
        import json

        port = free_port()
        worker = tmp_path / "worker.py"
        worker.write_text(textwrap.dedent(self.WORKER))
        env = dict(os.environ, PYTHONPATH=REPO)

        def launch(node_rank):
            return subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "distributed_pytorch_tpu.elastic",
                    "--nnodes",
                    "1:2",
                    "--node-rank",
                    str(node_rank),
                    "--nproc-per-node",
                    "1",
                    "--rdzv-endpoint",
                    f"127.0.0.1:{port}",
                    "--heartbeat-interval",
                    "0.5",
                    "--heartbeat-timeout",
                    "4",
                    "--scale-down-grace",
                    "4",
                    "--max-restarts",
                    "2",
                    str(worker),
                ],
                env=env,
                cwd=tmp_path,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                start_new_session=True,  # killpg must reap agent AND worker
            )

        agent0 = launch(0)
        agent1 = launch(1)
        try:
            # Wait until node 1's worker is INSIDE epoch 1, then kill its
            # whole process group — agent and worker die for good.
            deadline = time.time() + 90
            while not (tmp_path / "start.1.1.w2").exists():
                assert time.time() < deadline, "epoch 1 never started"
                assert agent0.poll() is None, agent0.communicate()[1]
                time.sleep(0.1)
            os.killpg(os.getpgid(agent1.pid), signal.SIGKILL)

            out, err = agent0.communicate(timeout=120)
            assert agent0.returncode == 0, out + err
            assert "scale-down" in out, out
        finally:
            for a in (agent0, agent1):
                try:
                    os.killpg(os.getpgid(a.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass

        # Every completed epoch covers all 16 samples exactly once, under
        # whichever world size completed it.
        full = set(range(16))
        done = sorted(p.name for p in tmp_path.glob("done.*"))
        completed = {}
        for name in done:
            _, epoch, w = name.split(".")
            completed[int(epoch)] = int(w[1:])
        assert sorted(completed) == [0, 1, 2], done
        for epoch, w in completed.items():
            cov = []
            for r in range(w):
                cov.extend(
                    json.load(open(tmp_path / f"cov.{epoch}.{r}.w{w}"))
                )
            assert sorted(cov) == sorted(full), (epoch, w, cov)
            assert len(cov) == len(set(cov)), (epoch, cov)
        # The kill landed mid-epoch-1, so epochs 1 and 2 must have been
        # completed by the re-formed single-node world.
        assert completed[2] == 1, completed
        assert completed[1] == 1, completed
        assert completed[0] == 2, completed


@pytest.mark.slow
class TestScaleUp:
    """The reverse path: a node that revives AFTER the world scaled down
    joins the store, finds itself excluded from the settled membership,
    bumps the generation, and the world re-forms at full size."""

    WORKER = """
    import json, os, sys, time

    pid = int(os.environ["PROCESS_ID"])
    W = int(os.environ["NUM_PROCESSES"])
    N, EPOCHS = 16, 8

    start = 0
    if os.path.exists("state.json"):
        start = json.load(open("state.json"))["epochs_done"]

    for epoch in range(start, EPOCHS):
        open(f"start.{epoch}.{pid}.w{W}", "w").write("")
        time.sleep(1.0)
        idx = list(range(pid, N, W))
        with open(f"cov.{epoch}.{pid}.w{W}", "w") as f:
            json.dump(idx, f)
        deadline = time.time() + 60
        while not all(
            os.path.exists(f"cov.{epoch}.{r}.w{W}") for r in range(W)
        ):
            if time.time() > deadline:
                sys.exit(9)
            time.sleep(0.1)
        if pid == 0:
            open(f"done.{epoch}.w{W}", "w").write("")
            with open("state.json.tmp", "w") as f:
                json.dump({"epochs_done": epoch + 1}, f)
            os.replace("state.json.tmp", "state.json")
        time.sleep(0.2)
    """

    def test_revived_node_rejoins_and_world_regrows(self, tmp_path):
        port = free_port()
        worker = tmp_path / "worker.py"
        worker.write_text(textwrap.dedent(self.WORKER))
        env = dict(os.environ, PYTHONPATH=REPO)

        def launch(node_rank):
            return subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "distributed_pytorch_tpu.elastic",
                    "--nnodes",
                    "1:2",
                    "--node-rank",
                    str(node_rank),
                    "--nproc-per-node",
                    "1",
                    "--rdzv-endpoint",
                    f"127.0.0.1:{port}",
                    "--heartbeat-interval",
                    "0.5",
                    "--heartbeat-timeout",
                    "3",
                    "--scale-down-grace",
                    "3",
                    "--max-restarts",
                    "4",
                    str(worker),
                ],
                env=env,
                cwd=tmp_path,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                start_new_session=True,
            )

        agent0 = launch(0)
        agent1 = launch(1)
        agent1b = None
        try:
            deadline = time.time() + 90
            while not (tmp_path / "start.1.1.w2").exists():
                assert time.time() < deadline, "epoch 1 never started"
                time.sleep(0.1)
            os.killpg(os.getpgid(agent1.pid), signal.SIGKILL)

            # Wait for the scaled-down world to actually complete an epoch
            # (proof the world is running at w1), then revive node 1.
            deadline = time.time() + 90
            while not list(tmp_path.glob("done.*.w1")):
                assert time.time() < deadline, "never scaled down to w1"
                assert agent0.poll() is None, agent0.communicate()[1]
                time.sleep(0.1)
            agent1b = launch(1)

            # The revived agent must force a regrow: some LATER epoch
            # completes at w2 again.
            deadline = time.time() + 90
            while True:
                w1_done = {
                    int(p.name.split(".")[1])
                    for p in tmp_path.glob("done.*.w1")
                }
                w2_done = {
                    int(p.name.split(".")[1])
                    for p in tmp_path.glob("done.*.w2")
                }
                if w1_done and w2_done and max(w2_done) > min(w1_done):
                    break
                assert time.time() < deadline, (w1_done, w2_done)
                assert agent0.poll() is None, agent0.communicate()[1]
                time.sleep(0.2)

            out, err = agent0.communicate(timeout=120)
            assert agent0.returncode == 0, out + err
        finally:
            for a in (agent0, agent1, agent1b):
                if a is None:
                    continue
                try:
                    os.killpg(os.getpgid(a.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass


# ------------------------------------------------- live-JAX fault injection


class TestElasticTraining:
    """The reference's marquee behavior, end-to-end: a live JAX training
    worker dies mid-epoch, tpurun restarts the world, and training resumes
    from the snapshot with no loss divergence (reference
    ``multigpu_torchrun.py:30-40,57-65`` + torchrun's restart policy)."""

    KILL_WORKER = """
    '''Rung-4 training worker with deterministic mid-epoch fault injection.

    Process 1 of the first launch SIGKILLs itself partway through epoch 1's
    batch loop. SIGKILL cannot be caught or blocked, so the effect is
    identical to an external ``kill -9`` landing mid-step: the process
    vanishes instantly while its peer sits inside a cross-process collective.
    '''
    import os
    import runpy
    import signal
    import sys

    process_id = os.environ["PROCESS_ID"]
    restart = os.environ["TPURUN_RESTART_COUNT"]
    open(f"gen.{process_id}.{restart}", "w").write("ok")

    if process_id == "1" and restart == "0":
        import distributed_pytorch_tpu.training.trainer as trainer_mod

        steps = [0]
        original = trainer_mod.Trainer._run_batch

        def sabotaged(self, batch):
            steps[0] += 1
            if steps[0] > 21:  # 16 steps/epoch -> dies 6 steps into epoch 1
                os.kill(os.getpid(), signal.SIGKILL)
            return original(self, batch)

        trainer_mod.Trainer._run_batch = sabotaged

    sys.argv = [
        "multihost_pod.py", "3", "1",
        "--snapshot_path", "killtest.npz",
        "--fake_devices", "2",
    ]
    runpy.run_path(os.environ["POD_EXAMPLE"], run_name="__main__")
    """

    @pytest.mark.slow
    def test_sigkill_mid_epoch_restart_resume_parity(self, tmp_path):
        """SIGKILL a live JAX worker mid-epoch; assert restart-the-world,
        snapshot resume, and final losses identical to an uninterrupted run."""
        result = run_tpurun(
            tmp_path,
            self.KILL_WORKER,
            "--standalone",
            "--nproc-per-node",
            "2",
            "--max-restarts",
            "2",
            timeout=600,
            extra_env={
                "POD_EXAMPLE": os.path.join(REPO, "examples", "multihost_pod.py"),
                # Each worker presents 2 virtual chips -> a 4-chip world.
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "JAX_PLATFORMS": "cpu",
            },
        )
        assert result.returncode == 0, result.stdout + result.stderr

        # The world restarted exactly once: every worker ran at generation 0
        # AND at generation 1 (TPURUN_RESTART_COUNT bumped for all of them).
        markers = {p.name for p in tmp_path.glob("gen.*")}
        assert {"gen.0.0", "gen.1.0", "gen.0.1", "gen.1.1"} <= markers
        assert "restart 1/2" in result.stdout
        # The relaunched workers resumed from the epoch-0 snapshot, not step 0.
        assert "Resuming training from snapshot at Epoch 1" in result.stdout

        # Loss parity with an uninterrupted run of the same global workload
        # (one process, 4 virtual chips, same global batch of 128).
        single = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "examples", "multihost_pod.py"),
                "3", "1",
                "--snapshot_path", str(tmp_path / "uninterrupted.npz"),
                "--fake_devices", "4",
            ],
            cwd=tmp_path,
            env={
                **os.environ,
                "PYTHONPATH": REPO,
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            },
            capture_output=True,
            text=True,
            timeout=600,
        )
        assert single.returncode == 0, single.stdout + single.stderr

        killed = epoch_losses(result.stdout)
        clean = epoch_losses(single.stdout)
        assert set(killed) == {0, 1, 2}, f"epochs seen: {sorted(killed)}"
        for epoch, loss in clean.items():
            np.testing.assert_allclose(killed[epoch], loss, rtol=1e-6)

    @pytest.mark.slow
    def test_heartbeat_staleness_restarts_world(self, tmp_path):
        """A node that goes silent (SIGSTOP: process alive, heartbeats frozen)
        past --heartbeat-timeout is declared dead by its peer, who bumps the
        generation; when the node wakes it rejoins the restarted world."""
        port = free_port()
        worker = tmp_path / "worker.py"
        worker.write_text(
            textwrap.dedent(
                """
                import os, time
                pid = os.environ["PROCESS_ID"]
                restart = int(os.environ["TPURUN_RESTART_COUNT"])
                open(f"started.{pid}.{restart}", "w").write("ok")
                if restart == 0:
                    time.sleep(300)  # hung world: only node failure ends it
                open(f"done.{pid}.{restart}", "w").write("ok")
                """
            )
        )
        env = dict(os.environ, PYTHONPATH=REPO)

        def launch(node_rank):
            return subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "distributed_pytorch_tpu.elastic",
                    "--nnodes", "2",
                    "--node-rank", str(node_rank),
                    "--nproc-per-node", "1",
                    "--rdzv-endpoint", f"127.0.0.1:{port}",
                    "--heartbeat-interval", "0.3",
                    "--heartbeat-timeout", "3",
                    str(worker),
                ],
                env=env,
                cwd=tmp_path,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )

        agents = [launch(0), launch(1)]
        try:
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and not (
                (tmp_path / "started.0.0").exists()
                and (tmp_path / "started.1.0").exists()
            ):
                time.sleep(0.1)
            assert (tmp_path / "started.1.0").exists(), "world never started"

            os.kill(agents[1].pid, signal.SIGSTOP)  # node 1 goes silent
            time.sleep(6)  # well past heartbeat_timeout
            os.kill(agents[1].pid, signal.SIGCONT)

            out0, err0 = agents[0].communicate(timeout=120)
            out1, err1 = agents[1].communicate(timeout=120)
        finally:
            for a in agents:
                if a.poll() is None:
                    a.kill()
                    a.wait()
        assert agents[0].returncode == 0, out0 + err0
        assert agents[1].returncode == 0, out1 + err1
        assert "heartbeat lost" in out0
        # Both nodes' workers ran again at a bumped restart count and finished.
        assert any((tmp_path / f"done.0.{r}").exists() for r in range(1, 4))
        assert any((tmp_path / f"done.1.{r}").exists() for r in range(1, 4))


class TestScaleDownLiveTraining:
    """Scale-down with LIVE JAX training: 2 single-worker nodes (2 fake
    chips each), node 1 killed for good at the top of epoch 1; the world
    re-forms at size 1 and the worker resumes from the snapshot with
    NUM_PROCESSES=1 (its ShardedLoader re-shards). Epoch 0 is checked to
    1e-6 against an uninterrupted run AT THE ORIGINAL WORLD (same 128
    global batch); the post-shrink epochs have no single-world reference —
    the example's batch is per-chip, so the global batch legitimately
    halves — and are asserted to run at w1 with decreasing losses."""

    WORKER = """
    import os
    import runpy
    import sys
    import time

    import distributed_pytorch_tpu.training.trainer as trainer_mod

    process_id = os.environ["PROCESS_ID"]
    world = os.environ["NUM_PROCESSES"]
    restart = os.environ["TPURUN_RESTART_COUNT"]
    open(f"world.{process_id}.w{world}.r{restart}", "w").write("ok")

    original = trainer_mod.Trainer._run_epoch

    def marked(self, epoch):
        open(f"epoch.{process_id}.{epoch}.w{world}", "w").write("ok")
        if process_id == "1" and restart == "0" and epoch == 1:
            # Deterministic kill gate: park HERE (before any epoch-1 step)
            # until the test SIGKILLs this node's process group — epoch 1
            # can never complete in the 2-node world, so the race the
            # marker+poll alone would leave is closed.
            time.sleep(3600)
        return original(self, epoch)

    trainer_mod.Trainer._run_epoch = marked

    sys.argv = [
        "multihost_pod.py", "3", "1",
        "--snapshot_path", "sd.npz",
        "--fake_devices", "2",
    ]
    runpy.run_path(os.environ["POD_EXAMPLE"], run_name="__main__")
    """

    @pytest.mark.slow
    def test_world_shrinks_and_losses_match_uninterrupted(self, tmp_path):
        worker = tmp_path / "worker.py"
        worker.write_text(textwrap.dedent(self.WORKER))
        port = free_port()
        env = dict(
            os.environ,
            PYTHONPATH=REPO,
            POD_EXAMPLE=os.path.join(REPO, "examples", "multihost_pod.py"),
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
            JAX_PLATFORMS="cpu",
        )

        def launch(node_rank):
            return subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "distributed_pytorch_tpu.elastic",
                    "--nnodes",
                    "1:2",
                    "--node-rank",
                    str(node_rank),
                    "--nproc-per-node",
                    "1",
                    "--rdzv-endpoint",
                    f"127.0.0.1:{port}",
                    "--heartbeat-interval",
                    "0.5",
                    "--heartbeat-timeout",
                    "5",
                    "--scale-down-grace",
                    "5",
                    "--max-restarts",
                    "2",
                    str(worker),
                ],
                env=env,
                cwd=tmp_path,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                start_new_session=True,
            )

        agent0 = launch(0)
        agent1 = launch(1)
        try:
            deadline = time.time() + 240
            while not (tmp_path / "epoch.1.1.w2").exists():
                assert time.time() < deadline, "node 1 never reached epoch 1"
                assert agent0.poll() is None, agent0.communicate()[1]
                # agent1 must be ALIVE until the deliberate kill — an early
                # crash should fail fast with its stderr, not burn the
                # deadline.
                assert agent1.poll() is None, agent1.communicate()[1]
                time.sleep(0.2)
            os.killpg(os.getpgid(agent1.pid), signal.SIGKILL)

            out, err = agent0.communicate(timeout=600)
            assert agent0.returncode == 0, out + err
        finally:
            for a in (agent0, agent1):
                try:
                    os.killpg(os.getpgid(a.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass

        assert "scale-down" in out, out
        # The re-formed world really was size 1 (NUM_PROCESSES env contract).
        assert (tmp_path / "world.0.w1.r1").exists(), sorted(
            p.name for p in tmp_path.glob("world.*")
        )
        assert "Resuming training from snapshot at Epoch" in out
        # Post-shrink epochs really ran in the 1-process world.
        assert (tmp_path / "epoch.0.1.w1").exists()
        assert (tmp_path / "epoch.0.2.w1").exists()

        survived = epoch_losses(out)
        assert set(survived) == {0, 1, 2}, sorted(survived)
        # Post-shrink training is real learning, not a stalled loop.
        assert survived[2] < survived[1] < survived[0], survived

        # Loss parity AT THE ORIGINAL WORLD: epoch 0 (trained 2 procs x 2
        # chips) must match an uninterrupted single-process 4-chip run —
        # same global batch (the example's batch is per-chip, so the
        # POST-shrink epochs legitimately run a smaller global batch and
        # have no single-world reference).
        single = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "examples", "multihost_pod.py"),
                "3", "1",
                "--snapshot_path", str(tmp_path / "clean.npz"),
                "--fake_devices", "4",
            ],
            cwd=tmp_path,
            env={**env, "XLA_FLAGS": "--xla_force_host_platform_device_count=4"},
            capture_output=True,
            text=True,
            timeout=600,
        )
        assert single.returncode == 0, single.stdout + single.stderr
        clean = epoch_losses(single.stdout)
        np.testing.assert_allclose(survived[0], clean[0], rtol=1e-6)


class TestCompletedWorldRace:
    """ADVICE r04: a revived latecomer must not bump the generation of a
    world that already completed, and a locally-succeeded agent must not
    honor a stray bump before the done counter has had a chance to fill —
    otherwise some agents exit 0 while others restart into a dead store."""

    @pytest.fixture()
    def rig(self):
        from distributed_pytorch_tpu.elastic.agent import (
            ElasticAgent,
            ElasticConfig,
        )
        from distributed_pytorch_tpu.elastic.store import (
            KVStoreClient,
            KVStoreServer,
        )

        port = free_port()
        with KVStoreServer(port):
            with KVStoreClient("127.0.0.1", port) as admin:
                cfg = ElasticConfig(
                    nnodes=3, node_rank=2, rdzv_host="127.0.0.1",
                    rdzv_port=port,
                )
                agent = ElasticAgent(cfg, ["true"])
                try:
                    yield agent, admin
                finally:
                    agent.store.close()

    def test_latecomer_does_not_bump_completed_world(self, rig):
        from distributed_pytorch_tpu.elastic.agent import (
            DONE_PREFIX,
            GEN_KEY,
            WORLD_PREFIX,
            WorldCompleted,
        )

        agent, admin = rig
        admin.set(GEN_KEY, "0")
        admin.set(f"{WORLD_PREFIX}0", "0,1")  # settled without node 2
        admin.add(f"{DONE_PREFIX}0", 2)  # ...and fully completed
        with pytest.raises(WorldCompleted) as exc:
            agent._rendezvous_once(agent.cfg, time.monotonic())
        assert exc.value.finished
        assert int(admin.get(GEN_KEY)) == 0  # NOT bumped

    def test_latecomer_still_bumps_live_world(self, rig):
        from distributed_pytorch_tpu.elastic.agent import (
            DONE_PREFIX,
            GEN_KEY,
            WORLD_PREFIX,
        )
        from distributed_pytorch_tpu.elastic.agent import _Retry

        agent, admin = rig
        admin.set(GEN_KEY, "0")
        admin.set(f"{WORLD_PREFIX}0", "0,1")
        admin.add(f"{DONE_PREFIX}0", 1)  # one member still running
        with pytest.raises(_Retry):
            agent._rendezvous_once(agent.cfg, time.monotonic())
        assert int(admin.get(GEN_KEY)) == 1  # restart-the-world as before

    def test_await_world_done_survives_bump_when_counter_fills(
        self, rig, monkeypatch
    ):
        import distributed_pytorch_tpu.elastic.agent as agent_mod
        from distributed_pytorch_tpu.elastic.agent import DONE_PREFIX, GEN_KEY

        agent, admin = rig
        monkeypatch.setattr(agent_mod, "DONE_BUMP_GRACE", 5.0)
        admin.set(GEN_KEY, "8")  # bumped past our generation 7...
        admin.add(f"{DONE_PREFIX}7", 2)
        # ...while the last member's DONE lands shortly after.
        t = threading.Thread(
            target=lambda: (time.sleep(1.5), admin.add(f"{DONE_PREFIX}7", 1))
        )
        t.start()
        try:
            assert agent._await_world_done(7, 3) == "done"
        finally:
            t.join()

    def test_await_world_done_restarts_when_counter_never_fills(
        self, rig, monkeypatch
    ):
        import distributed_pytorch_tpu.elastic.agent as agent_mod
        from distributed_pytorch_tpu.elastic.agent import DONE_PREFIX, GEN_KEY

        agent, admin = rig
        monkeypatch.setattr(agent_mod, "DONE_BUMP_GRACE", 1.0)
        admin.set(GEN_KEY, "8")
        admin.add(f"{DONE_PREFIX}7", 1)  # a member truly failed: never fills
        start = time.monotonic()
        assert agent._await_world_done(7, 3) == "restart"
        assert time.monotonic() - start >= 1.0  # grace observed

    def test_finished_marker_alone_is_terminal(self, rig):
        from distributed_pytorch_tpu.elastic.agent import (
            FINISHED_PREFIX,
            GEN_KEY,
        )

        agent, admin = rig
        admin.set(GEN_KEY, "9")  # even with a bump in place
        admin.set(f"{FINISHED_PREFIX}7", "1")
        assert agent._await_world_done(7, 3) == "done"

    def test_fatal_is_honored_immediately(self, rig):
        from distributed_pytorch_tpu.elastic.agent import (
            DONE_PREFIX,
            FATAL_KEY,
            GEN_KEY,
        )

        agent, admin = rig
        admin.set(GEN_KEY, "7")  # not bumped
        admin.set(FATAL_KEY, "node1-restarts-exhausted")
        admin.add(f"{DONE_PREFIX}7", 1)
        start = time.monotonic()
        assert agent._await_world_done(7, 3) == "restart"
        # No stall-window wait on the fatal path (one wait_ge poll only).
        assert time.monotonic() - start < 3.0
