"""Elastic launcher (tpurun) + native rendezvous store tests.

Covers the torchrun-equivalent layer the reference outsources
(SURVEY.md §3.3): env-var contract, rendezvous via the C++ TCP store,
failure detection, and restart-the-world recovery with TPURUN_RESTART_COUNT.

Workers here are tiny pure-Python scripts (no jax import) so the tests run in
seconds; the full train-resume integration lives in
``tests/test_integration_multiprocess.py``.
"""

import os
import socket
import subprocess
import sys
import textwrap
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ----------------------------------------------------------------- KV store


class TestKVStore:
    @pytest.fixture()
    def store(self):
        from distributed_pytorch_tpu.elastic.store import KVStoreClient, KVStoreServer

        port = free_port()
        with KVStoreServer(port):
            with KVStoreClient("127.0.0.1", port) as client:
                yield client, port

    def test_set_get_roundtrip_with_spaces(self, store):
        client, _ = store
        client.set("a/key", "value with spaces + specials%")
        assert client.get("a/key") == "value with spaces + specials%"
        assert client.get("missing") is None

    def test_atomic_add(self, store):
        client, _ = store
        assert client.add("ctr", 2) == 2
        assert client.add("ctr", 3) == 5

    def test_wait_ge_blocks_until_target(self, store):
        from distributed_pytorch_tpu.elastic.store import KVStoreClient

        client, port = store
        assert client.wait_ge("joined", 2, timeout=0.2) is None  # times out

        def join_later():
            time.sleep(0.2)
            with KVStoreClient("127.0.0.1", port) as c2:
                c2.add("joined", 1)
                c2.add("joined", 1)

        threading.Thread(target=join_later).start()
        assert client.wait_ge("joined", 2, timeout=10) == 2

    def test_concurrent_adds_from_many_clients(self, store):
        """The rendezvous join-count must be exact under concurrency."""
        from distributed_pytorch_tpu.elastic.store import KVStoreClient

        client, port = store
        n_clients, n_adds = 8, 25

        def hammer():
            with KVStoreClient("127.0.0.1", port) as c:
                for _ in range(n_adds):
                    c.add("hammer", 1)

        threads = [threading.Thread(target=hammer) for _ in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert client.get("hammer") == str(n_clients * n_adds)

    def test_keys_prefix(self, store):
        client, _ = store
        client.set("hb/0", "x")
        client.set("hb/1", "y")
        client.set("other", "z")
        assert sorted(client.keys("hb/")) == ["hb/0", "hb/1"]


# ----------------------------------------------------------------- agent


def run_tpurun(tmp_path, worker_src: str, *args: str, timeout: float = 120):
    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent(worker_src))
    env = dict(os.environ, PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "distributed_pytorch_tpu.elastic", *args, str(worker)],
        env=env,
        cwd=tmp_path,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


class TestElasticAgent:
    def test_standalone_env_contract(self, tmp_path):
        """Workers see the full torchrun-style env (SURVEY §2: ddp_setup env form)."""
        result = run_tpurun(
            tmp_path,
            """
            import os
            pid = os.environ["PROCESS_ID"]
            assert os.environ["NUM_PROCESSES"] == "3"
            assert os.environ["LOCAL_RANK"] == pid  # single node: local == global
            assert os.environ["TPURUN_RESTART_COUNT"] == "0"
            assert ":" in os.environ["COORDINATOR_ADDRESS"]
            open(f"saw.{pid}", "w").write("ok")
            """,
            "--standalone",
            "--nproc-per-node",
            "3",
        )
        assert result.returncode == 0, result.stderr
        assert sorted(p.name for p in tmp_path.glob("saw.*")) == [
            "saw.0",
            "saw.1",
            "saw.2",
        ]

    def test_restart_on_worker_failure(self, tmp_path):
        """One worker fails at generation 0; the whole world restarts and
        succeeds at generation 1 (torchrun restart-all semantics)."""
        result = run_tpurun(
            tmp_path,
            """
            import os, sys
            restart = int(os.environ["TPURUN_RESTART_COUNT"])
            pid = os.environ["PROCESS_ID"]
            if restart == 0 and pid == "1":
                sys.exit(7)
            open(f"done.{pid}.{restart}", "w").write("ok")
            """,
            "--standalone",
            "--nproc-per-node",
            "2",
            "--max-restarts",
            "2",
        )
        assert result.returncode == 0, result.stderr
        # Generation 1 ran both workers; worker 0's gen-0 file may or may not
        # survive the kill, but both gen-1 files must exist.
        names = {p.name for p in tmp_path.glob("done.*")}
        assert {"done.0.1", "done.1.1"} <= names

    def test_restarts_exhausted_is_fatal(self, tmp_path):
        result = run_tpurun(
            tmp_path,
            """
            import sys
            sys.exit(3)  # always fails
            """,
            "--standalone",
            "--nproc-per-node",
            "1",
            "--max-restarts",
            "1",
        )
        assert result.returncode == 1
        assert "giving up" in result.stderr

    def test_two_node_rendezvous(self, tmp_path):
        """Two agents on one machine = the sbatch_run.sh multinode shape."""
        port = free_port()
        worker = tmp_path / "worker.py"
        worker.write_text(
            textwrap.dedent(
                """
                import os
                pid = os.environ["PROCESS_ID"]
                assert os.environ["NUM_PROCESSES"] == "4"
                open(f"n.{pid}", "w").write(os.environ["LOCAL_RANK"])
                """
            )
        )
        env = dict(os.environ, PYTHONPATH=REPO)

        def launch(node_rank):
            return subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "distributed_pytorch_tpu.elastic",
                    "--nnodes",
                    "2",
                    "--node-rank",
                    str(node_rank),
                    "--nproc-per-node",
                    "2",
                    "--rdzv-endpoint",
                    f"127.0.0.1:{port}",
                    str(worker),
                ],
                env=env,
                cwd=tmp_path,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )

        agents = [launch(0), launch(1)]
        for a in agents:
            out, err = a.communicate(timeout=120)
            assert a.returncode == 0, err
        assert sorted(p.name for p in tmp_path.glob("n.*")) == [
            "n.0",
            "n.1",
            "n.2",
            "n.3",
        ]
        # LOCAL_RANK is per-node: global 0,1 -> node0 local 0,1; global 2,3 -> node1.
        assert (tmp_path / "n.2").read_text() == "0"
        assert (tmp_path / "n.3").read_text() == "1"
