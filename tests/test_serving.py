"""Serving subsystem tests: continuous-batching parity with offline
generate(), scheduler/allocator invariants under randomized load, preemption
determinism, and admission control. All on CPU (conftest pins
JAX_PLATFORMS=cpu) — the engine is deterministic there by construction.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.generation import generate
from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.serving import (
    HostPageTier,
    InferenceEngine,
    OutOfPages,
    PagedBlockAllocator,
    PrefixCache,
    QueueFull,
    Request,
    RequestTooLong,
    SamplingParams,
    Scheduler,
)
from distributed_pytorch_tpu.serving.kv_cache import NULL_PAGE, BlockTable


def tiny_lm(**kw):
    return TransformerLM(
        vocab_size=48, d_model=16, n_layers=2, n_heads=2, d_ff=32,
        dtype=jnp.float32, **kw,
    )


@pytest.fixture(scope="module")
def model_and_params():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def offline_greedy(model, params, prompt, max_new):
    out = generate(
        model, params, jnp.asarray([prompt], jnp.int32),
        max_new_tokens=max_new, temperature=0.0, rng=jax.random.PRNGKey(0),
    )
    return np.asarray(out)[0, len(prompt):].tolist()


# ---------------------------------------------------------------- allocator


class TestPagedBlockAllocator:
    def test_null_page_reserved(self):
        alloc = PagedBlockAllocator(4)
        pages = alloc.allocate(3)
        assert NULL_PAGE not in pages
        assert sorted(pages) == [1, 2, 3]

    def test_all_or_nothing(self):
        alloc = PagedBlockAllocator(4)
        alloc.allocate(2)
        with pytest.raises(OutOfPages):
            alloc.allocate(2)
        # the failed call took nothing
        assert alloc.num_free == 1
        alloc.check_invariants()

    def test_double_free_detected(self):
        alloc = PagedBlockAllocator(4)
        pages = alloc.allocate(1)
        alloc.free(pages)
        with pytest.raises(AssertionError):
            alloc.free(pages)

    def test_block_table_grow_and_release(self):
        alloc = PagedBlockAllocator(8)
        table = BlockTable()
        assert table.ensure(5, 2, alloc) == 3  # ceil(5/2)
        assert table.ensure(6, 2, alloc) == 0  # already covered
        assert table.ensure(7, 2, alloc) == 1
        row = table.as_row(6)
        assert row.dtype == np.int32
        assert list(row[4:]) == [NULL_PAGE, NULL_PAGE]
        assert table.release(alloc) == 4
        alloc.check_invariants()
        assert alloc.num_free == 7


def assert_gauges_match_sweep(alloc):
    """The O(1) page-state gauges (what the engine exports every step)
    must equal an independent full sweep of the allocator's structures."""
    c = alloc.counters()
    assert c["pages_free"] == len(alloc._free)
    assert c["pages_referenced"] == len(alloc._ref)
    assert c["pages_cached_idle"] == len(alloc._idle)
    assert (
        c["pages_free"] + c["pages_referenced"] + c["pages_cached_idle"]
        == alloc.num_pages - 1
    )


# ---------------------------------------------------------- scheduler props


class TestSchedulerInvariants:
    def _drive(self, sched, plan):
        """Simulate the device side of one plan: complete every prefill
        chunk, then emit an arbitrary token for every decode slot."""
        finished = []
        for slot, chunk in plan.prefill:
            sched.note_prefilled(slot, chunk)
        for slot in plan.decode_slots:
            done = sched.note_decoded(slot, token=1, now=0.0)
            if done is not None:
                sched.retire(done, now=0.0)
                finished.append(done)
        return finished

    def test_no_block_leaked_over_randomized_cycles(self):
        """1k randomized submit/step cycles against a small pool: allocator
        invariants hold at every step and every page is free at the end."""
        rng = random.Random(1234)
        alloc = PagedBlockAllocator(17)
        sched = Scheduler(
            alloc, max_slots=4, page_size=2, pages_per_seq=8,
            token_budget=8, max_prefill_chunk=4, debug=True,
        )
        next_id = 0
        live = {}
        for cycle in range(1000):
            if rng.random() < 0.4 and len(live) < 32:
                prompt = [rng.randrange(48) for _ in range(rng.randint(1, 9))]
                req = Request(
                    req_id=next_id, prompt=prompt,
                    params=SamplingParams(
                        max_new_tokens=rng.randint(1, 16 - len(prompt))
                    ),
                )
                live[next_id] = req
                sched.add(req)
                next_id += 1
            plan = self._drive(sched, sched.schedule())
            for req in plan:
                del live[req.req_id]
            alloc.check_invariants()
            assert_gauges_match_sweep(alloc)
            for req in live.values():
                # every live table is page-aligned with what's cached
                assert len(req.table) >= PagedBlockAllocator.pages_needed(
                    req.len_cached, 2
                )
        # drain whatever is left
        for _ in range(2000):
            if not sched.has_work:
                break
            for req in self._drive(sched, sched.schedule()):
                del live[req.req_id]
        assert not sched.has_work
        assert not live
        alloc.check_invariants()
        assert_gauges_match_sweep(alloc)
        assert alloc.num_free == 16  # every allocatable page returned

    def test_preemption_only_evicts_lower_priority(self):
        """With a pool that fits one sequence, the oldest request finishes
        first — newer ones get preempted, never the oldest."""
        alloc = PagedBlockAllocator(5)  # 4 usable pages
        sched = Scheduler(
            alloc, max_slots=2, page_size=2, pages_per_seq=4,
            token_budget=8, max_prefill_chunk=4, debug=True,
        )
        reqs = [
            Request(req_id=i, prompt=[1, 2, 3],
                    params=SamplingParams(max_new_tokens=5))
            for i in range(2)
        ]
        for r in reqs:
            sched.add(r)
        order = []
        for _ in range(200):
            if not sched.has_work:
                break
            order.extend(
                r.req_id
                for r in TestSchedulerInvariants._drive(self, sched,
                                                        sched.schedule())
            )
        assert order and order[0] == 0, "oldest request must finish first"
        assert reqs[0].preempt_count == 0, (
            "highest-priority request must never be preempted"
        )
        assert reqs[1].preempt_count > 0
        alloc.check_invariants()
        assert alloc.num_free == 4


# ------------------------------------------------------------ prefix cache


class TestPrefixCacheTrie:
    def test_full_chain_lookup_refs_pages(self):
        alloc = PagedBlockAllocator(8)
        cache = PrefixCache(alloc, page_size=2)
        p1, p2 = alloc.allocate(2)
        n1, registered = cache.register_full(PrefixCache.ROOT, (1, 2), p1)
        assert registered
        n2, _ = cache.register_full(n1, (3, 4), p2)
        alloc.free([p1, p2])  # refcount 0 -> cached-idle, not freed
        assert alloc.num_idle == 2
        pages, matched, node = cache.lookup([1, 2, 3, 4, 5])
        assert pages == [p1, p2] and matched == 4 and node == n2
        assert alloc.refcount(p1) == 1 and alloc.refcount(p2) == 1
        alloc.check_invariants()

    def test_lookup_never_consumes_last_token(self):
        """The decode step must always be fed at least one real token, so
        a fully cached prompt still leaves its final token uncached."""
        alloc = PagedBlockAllocator(8)
        cache = PrefixCache(alloc, page_size=2)
        (p1,) = alloc.allocate(1)
        cache.register_full(PrefixCache.ROOT, (1, 2), p1)
        alloc.free([p1])
        pages, matched, _ = cache.lookup([1, 2])  # limit is len - 1 = 1
        assert pages == [] and matched == 0
        assert alloc.num_idle == 1  # untouched

    def test_partial_match_requires_complete_tuple(self):
        """A prefix-of-partial hit would hand out a page whose registered
        tail diverges from the new prompt — must be a miss."""
        alloc = PagedBlockAllocator(8)
        cache = PrefixCache(alloc, page_size=4)
        (p1,) = alloc.allocate(1)
        assert cache.register_partial(PrefixCache.ROOT, (7, 8, 9), p1)
        alloc.free([p1])
        pages, matched, _ = cache.lookup([7, 8, 1, 1, 1])
        assert matched == 0 and pages == []
        pages, matched, _ = cache.lookup([7, 8, 9, 1, 1])
        assert matched == 3 and pages == [p1]
        alloc.check_invariants()

    def test_register_dedupes_and_existing_page_wins(self):
        alloc = PagedBlockAllocator(8)
        cache = PrefixCache(alloc, page_size=2)
        p1, p2 = alloc.allocate(2)
        n1, first = cache.register_full(PrefixCache.ROOT, (1, 2), p1)
        n2, second = cache.register_full(PrefixCache.ROOT, (1, 2), p2)
        assert first and not second and n1 == n2
        alloc.free([p1, p2])
        assert alloc.num_idle == 1  # p2 stayed private and freed normally
        pages, _, _ = cache.lookup([1, 2, 3])
        assert pages == [p1]
        alloc.check_invariants()

    def test_eviction_removes_trie_entries(self):
        alloc = PagedBlockAllocator(4)  # 3 usable pages
        cache = PrefixCache(alloc, page_size=2)
        pages = alloc.allocate(3)
        node = PrefixCache.ROOT
        for i, p in enumerate(pages):
            node, _ = cache.register_full(node, (i, i), p)
        alloc.free(pages)
        assert alloc.num_idle == 3
        alloc.allocate(2)  # pressure: evicts the two LRU-oldest idle pages
        assert alloc.evictions == 2
        assert cache.num_nodes == 1
        # the chain head was evicted first, so the survivor is unreachable
        _, matched, _ = cache.lookup([0, 0, 1, 1, 2, 2, 9])
        assert matched == 0
        alloc.check_invariants()


class TestCowAllocatorProperty:
    PREFIXES = [[1, 2, 3, 4, 5, 6, 7], [1, 2, 3, 9, 9], [4, 4]]

    def test_randomized_interleaving_no_leaks_refcounts_exact(self):
        """1.2k randomized submit/prefill/decode/retire/preempt/evict
        cycles over the refcounted CoW allocator with prefix caching on a
        deliberately tiny pool: after every cycle the allocator invariants
        hold AND every page's refcount equals the number of live block
        tables holding it; at drain nothing leaked. A host page tier
        (deliberately smaller than the churn needs) rides the same
        cycles, so spills and fetches race device eviction — its O(1)
        free/resident gauges are cross-asserted against the O(n) sweep
        after every cycle too, and it must be quiescent at drain."""
        rng = random.Random(99)
        alloc = PagedBlockAllocator(21)
        cache = PrefixCache(alloc, page_size=2)
        pool = np.zeros((21, 2, 1, 2), np.float32)
        tier = HostPageTier(
            {"target": pool}, num_host_pages=6, page_size=2,
            gather_fn=lambda page: {"target": pool[page]},
        )
        cache.host = tier
        sched = Scheduler(
            alloc, max_slots=4, page_size=2, pages_per_seq=8,
            token_budget=8, max_prefill_chunk=4,
            prefix_cache=cache, debug=True,
        )
        next_id = 0
        live = {}

        def check_refcounts():
            readers = {}
            for req in sched.running:
                for p in req.table.pages:
                    readers[p] = readers.get(p, 0) + 1
            for p in range(1, alloc.num_pages):
                assert alloc.refcount(p) == readers.get(p, 0), (
                    f"page {p}: refcount {alloc.refcount(p)} != "
                    f"{readers.get(p, 0)} readers"
                )

        def drive_one():
            plan = sched.schedule()
            # Mirror the engine's step order for the host tier: drain the
            # spills this schedule staged, then execute its fetches
            # (stage chunks, unpin, clear the fetch-pending guard).
            tier.drain_spills()
            for key, page, _parent, _toks, _node in plan.fetches:
                tier.chunks(key)
                tier.unpin(key)
                cache.fetch_pending.discard(page)
            for slot, chunk in plan.prefill:
                sched.note_prefilled(slot, chunk)
            for slot in plan.decode_slots:
                # tiny token alphabet so generated streams collide and the
                # trie caches (and CoW-shares) decode-time pages too
                done = sched.note_decoded(
                    slot, token=rng.randrange(4), now=0.0
                )
                if done is not None:
                    sched.retire(done, now=0.0)
                    del live[done.req_id]

        def check_host_gauges():
            # O(1) gauges vs an independent O(n) sweep, plus the tier's
            # own partition invariants — same contract as the allocator.
            assert tier.pages_resident == len(tier._entries)
            assert tier.pages_free == len(tier._free_slots)
            assert tier.pages_resident + tier.pages_free == tier.capacity
            tier.check_invariants()

        for _ in range(1200):
            if rng.random() < 0.45 and len(live) < 40:
                prefix = self.PREFIXES[rng.randrange(3)]
                tail = [rng.randrange(48) for _ in range(rng.randint(0, 5))]
                prompt = (prefix + tail)[:11]
                req = Request(
                    req_id=next_id, prompt=prompt,
                    params=SamplingParams(
                        max_new_tokens=rng.randint(1, 16 - len(prompt)),
                    ),
                )
                live[next_id] = req
                sched.add(req)
                next_id += 1
            drive_one()
            alloc.check_invariants()
            assert_gauges_match_sweep(alloc)
            check_refcounts()
            check_host_gauges()
        for _ in range(4000):
            if not sched.has_work:
                break
            drive_one()
        assert not sched.has_work and not live
        alloc.check_invariants()
        assert_gauges_match_sweep(alloc)
        check_refcounts()
        check_host_gauges()
        tier.assert_quiescent()
        assert alloc.num_allocated == 0
        assert alloc.num_free == 20, "pages leaked"
        assert cache.stats()["prefix_hit_rate"] > 0
        assert alloc.evictions > 0, "pool was sized to force eviction"
        s = cache.stats()
        assert tier.spills > 0, "churn was sized to force spills"
        assert tier.fetches > 0 and s["prefix_tokens_hit_host"] > 0, (
            "churn was sized so host fetches race device eviction"
        )
        assert tier.host_evictions > 0, (
            "host tier was sized smaller than the spill stream"
        )


# ------------------------------------------------------------- engine parity


class TestEngineParity:
    PROMPTS = [[5, 7, 11, 2, 9, 3], [1, 4, 8], [2, 2, 3, 17, 40], [6, 1, 9, 9]]

    def test_continuous_batching_matches_offline_generate(
        self, model_and_params
    ):
        """Greedy continuous batching — including requests submitted
        mid-flight — is token-identical to each prompt decoded alone with
        offline generate()."""
        model, params = model_and_params
        refs = [
            offline_greedy(model, params, p, 14 - len(p))
            for p in self.PROMPTS
        ]
        eng = InferenceEngine(
            model, params, max_slots=4, max_seq_len=64, page_size=4,
            token_budget=16, max_prefill_chunk=8,
        )
        ids = [
            eng.submit(p, SamplingParams(max_new_tokens=14 - len(p)))
            for p in self.PROMPTS[:2]
        ]
        for _ in range(3):
            eng.step()  # the late submissions join a half-drained batch
        ids += [
            eng.submit(p, SamplingParams(max_new_tokens=14 - len(p)))
            for p in self.PROMPTS[2:]
        ]
        eng.run()
        for rid, ref in zip(ids, refs):
            assert eng.poll(rid).generated == ref
        stats = eng.stats()
        assert stats["requests_completed"] == 4
        assert stats["pages_allocated"] == 0

    def test_preempted_sequence_reproduces_identical_tokens(
        self, model_and_params
    ):
        """A pool too small for all requests forces preemption; resumed
        sequences still emit exactly the offline token stream."""
        model, params = model_and_params
        prompts = self.PROMPTS[:3]
        refs = [offline_greedy(model, params, p, 8) for p in prompts]
        eng = InferenceEngine(
            model, params, max_slots=3, max_seq_len=16, page_size=2,
            num_pages=10, token_budget=8, max_prefill_chunk=4,
        )
        ids = [
            eng.submit(p, SamplingParams(max_new_tokens=8)) for p in prompts
        ]
        eng.run()
        assert eng.stats()["preemptions"] > 0, (
            "pool was sized to force preemption"
        )
        assert any(eng.poll(r).preempt_count > 0 for r in ids)
        for rid, ref in zip(ids, refs):
            assert eng.poll(rid).generated == ref
        eng.allocator.check_invariants()
        assert eng.allocator.num_free == 9

    def test_sampled_stream_independent_of_batch_composition(
        self, model_and_params
    ):
        """fold_in(seed, token_index) keys: the same request samples the
        same tokens whether it runs alone or beside other requests."""
        model, params = model_and_params
        sp = SamplingParams(max_new_tokens=10, temperature=1.0, seed=42)
        eng = InferenceEngine(model, params, max_slots=2, max_seq_len=32,
                              page_size=4)
        solo = eng.submit([5, 7, 11], sp)
        eng.run()
        eng2 = InferenceEngine(model, params, max_slots=2, max_seq_len=32,
                               page_size=4)
        eng2.submit(
            [1, 2, 3, 4],
            SamplingParams(max_new_tokens=6, temperature=0.7, seed=7),
        )
        both = eng2.submit([5, 7, 11], sp)
        eng2.run()
        assert eng.poll(solo).generated == eng2.poll(both).generated

    def test_stop_token_ends_request_early(self, model_and_params):
        model, params = model_and_params
        ref = offline_greedy(model, params, [6, 1, 9, 9], 8)
        stop = ref[2]
        assert stop not in ref[:2], "test needs a stop token unique so far"
        eng = InferenceEngine(model, params, max_slots=2, max_seq_len=32,
                              page_size=4)
        rid = eng.submit(
            [6, 1, 9, 9],
            SamplingParams(max_new_tokens=8, stop_token=stop),
        )
        eng.run()
        assert eng.poll(rid).generated == ref[:3]  # stop token included


# ------------------------------------------------------ prefix-cache parity


class TestPrefixCachingParity:
    PREFIX = [5, 7, 11, 2, 9, 3, 8, 1]  # two full pages at page_size=4

    def _engine(self, model, params, **kw):
        kw.setdefault("max_slots", 4)
        kw.setdefault("max_seq_len", 64)
        kw.setdefault("page_size", 4)
        kw.setdefault("token_budget", 16)
        kw.setdefault("max_prefill_chunk", 8)
        kw.setdefault("debug", True)
        return InferenceEngine(model, params, **kw)

    def test_cached_generation_identical_to_cold(self, model_and_params):
        """A second request sharing the first's prompt prefix starts
        prefill past the cached pages yet emits the exact offline
        stream."""
        model, params = model_and_params
        p1 = self.PREFIX + [4, 6]
        p2 = self.PREFIX + [2, 13]
        ref1 = offline_greedy(model, params, p1, 6)
        ref2 = offline_greedy(model, params, p2, 6)
        eng = self._engine(model, params)
        a = eng.submit(p1, SamplingParams(max_new_tokens=6))
        eng.run()
        assert eng.stats()["prefix_tokens_hit"] == 0  # cold start
        b = eng.submit(p2, SamplingParams(max_new_tokens=6))
        eng.run()
        assert eng.poll(a).generated == ref1
        assert eng.poll(b).generated == ref2
        s = eng.stats()
        assert s["prefix_tokens_hit"] >= len(self.PREFIX)
        assert s["prefix_hit_rate"] > 0
        assert s["cached_tokens_admitted"] >= len(self.PREFIX)
        assert s["ttft_s_hit_count"] == 1 and s["ttft_s_miss_count"] == 1
        eng.allocator.check_invariants()

    def test_shared_partial_page_copy_on_write_parity(
        self, model_and_params
    ):
        """Two multi-turn continuations both extend the SAME cached partial
        page concurrently: the scheduler must copy-on-write for one of
        them, and both still match offline decode exactly."""
        model, params = model_and_params
        base = [5, 7, 11, 2, 9]
        ref0 = offline_greedy(model, params, base, 2)
        eng = self._engine(model, params)
        r0 = eng.submit(base, SamplingParams(max_new_tokens=2))
        eng.run()
        assert eng.poll(r0).generated == ref0
        # 6 cached tokens = 1 full page + 2 in the retired partial page
        hist = base + ref0[:1]
        conts = [hist + [3], hist + [17]]
        refs = [offline_greedy(model, params, p, 5) for p in conts]
        ids = [
            eng.submit(p, SamplingParams(max_new_tokens=5)) for p in conts
        ]
        eng.run()
        for rid, ref in zip(ids, refs):
            assert eng.poll(rid).generated == ref
        assert eng.scheduler.cow_copies >= 1
        assert eng.stats()["prefix_tokens_hit"] > 0
        eng.allocator.check_invariants()
        assert eng.allocator.num_allocated == 0

    def test_eviction_under_pressure_keeps_parity(self, model_and_params):
        """A pool too small to retain every retired prefix forces LRU
        eviction of cached-idle pages; outputs stay exact throughout."""
        model, params = model_and_params
        eng = self._engine(
            model, params, max_slots=2, max_seq_len=16, page_size=2,
            num_pages=10, token_budget=8, max_prefill_chunk=4,
        )
        prompts = [[i, i + 1, i + 2] for i in range(0, 12, 3)]
        refs = [offline_greedy(model, params, p, 5) for p in prompts]
        ids = []
        for p in prompts:
            ids.append(eng.submit(p, SamplingParams(max_new_tokens=5)))
            eng.run()
        for rid, ref in zip(ids, refs):
            assert eng.poll(rid).generated == ref
        assert eng.allocator.evictions > 0
        eng.allocator.check_invariants()

    def test_feature_toggles_do_not_change_tokens(self, model_and_params):
        """prefix_cache / overlap on or off is a pure perf choice: sampled
        streams are bitwise identical across all four combinations."""
        model, params = model_and_params
        prompts = TestEngineParity.PROMPTS
        outs = []
        for kw in (
            {},
            {"prefix_cache": False},
            {"overlap": False},
            {"prefix_cache": False, "overlap": False},
        ):
            eng = self._engine(model, params, **kw)
            ids = [
                eng.submit(
                    p,
                    SamplingParams(
                        max_new_tokens=14 - len(p), temperature=0.8, seed=3
                    ),
                )
                for p in prompts
            ]
            eng.run()
            outs.append([eng.poll(r).generated for r in ids])
        assert outs[0] == outs[1] == outs[2] == outs[3]

    def test_overlap_speculative_stop_leaves_no_leaks(
        self, model_and_params
    ):
        """Under overlap a stop token is detected one step late; the
        speculative dispatch past it must be rolled back without leaking
        pages or placeholder tokens."""
        model, params = model_and_params
        ref = offline_greedy(model, params, [6, 1, 9, 9], 8)
        stop = ref[2]
        eng = self._engine(model, params, overlap=True)
        rid = eng.submit(
            [6, 1, 9, 9], SamplingParams(max_new_tokens=8, stop_token=stop)
        )
        eng.run()
        assert eng.poll(rid).generated == ref[:3]
        req = eng.requests[rid]
        assert req.tokens == [6, 1, 9, 9] + ref[:3]
        assert not req.pending_idx
        assert eng.allocator.num_allocated == 0
        eng.allocator.check_invariants()

    def test_queue_token_budget_counts_only_uncached(self, model_and_params):
        """max_queue_tokens bounds queued UNCACHED prefill work: a prompt
        whose prefix is cached costs only its tail against the budget."""
        model, params = model_and_params
        long1 = self.PREFIX + [4, 6]
        long2 = self.PREFIX + [2, 13]
        eng = self._engine(model, params, max_queue_tokens=10)
        eng.submit(long1, SamplingParams(max_new_tokens=4))
        with pytest.raises(QueueFull):
            eng.submit(long2, SamplingParams(max_new_tokens=4))
        eng.run()
        # PREFIX's pages are cached now: the same prompts cost ~1 uncached
        # token each, so both fit the budget that just rejected one.
        eng.submit(long2, SamplingParams(max_new_tokens=4))
        eng.submit(self.PREFIX + [1, 1], SamplingParams(max_new_tokens=4))
        eng.run()
        assert eng.stats()["rejected_queue_full"] == 1


# --------------------------------------------------------------- admission


class TestAdmission:
    def test_queue_full_backpressure(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngine(
            model, params, max_slots=1, max_seq_len=16, page_size=4,
            max_queue=2,
        )
        eng.submit([1, 2], SamplingParams(max_new_tokens=2))
        eng.submit([3, 4], SamplingParams(max_new_tokens=2))
        with pytest.raises(QueueFull):
            eng.submit([5, 6], SamplingParams(max_new_tokens=2))
        eng.run()  # queue drains; admission reopens
        rid = eng.submit([5, 6], SamplingParams(max_new_tokens=2))
        eng.run()
        assert eng.poll(rid).finished
        assert eng.stats()["rejected_queue_full"] == 1

    def test_request_too_long_rejected_up_front(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngine(model, params, max_slots=1, max_seq_len=16,
                              page_size=4)
        with pytest.raises(RequestTooLong):
            eng.submit(list(range(12)), SamplingParams(max_new_tokens=8))

    def test_empty_prompt_rejected(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngine(model, params, max_slots=1, max_seq_len=16,
                              page_size=4)
        with pytest.raises(RequestTooLong):
            eng.submit([], SamplingParams(max_new_tokens=2))

    def test_latency_metrics_populated(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngine(model, params, max_slots=2, max_seq_len=32,
                              page_size=4)
        for p in ([1, 2, 3], [4, 5]):
            eng.submit(p, SamplingParams(max_new_tokens=4))
        eng.run()
        s = eng.stats()
        assert s["ttft_s_count"] == 2
        assert s["e2e_s_count"] == 2
        assert s["tpot_s_count"] == 2
        assert s["ttft_s_p50"] > 0
        assert s["tokens_generated"] == 8
