"""Serving subsystem tests: continuous-batching parity with offline
generate(), scheduler/allocator invariants under randomized load, preemption
determinism, and admission control. All on CPU (conftest pins
JAX_PLATFORMS=cpu) — the engine is deterministic there by construction.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.generation import generate
from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.serving import (
    InferenceEngine,
    OutOfPages,
    PagedBlockAllocator,
    QueueFull,
    Request,
    RequestTooLong,
    SamplingParams,
    Scheduler,
)
from distributed_pytorch_tpu.serving.kv_cache import NULL_PAGE, BlockTable


def tiny_lm(**kw):
    return TransformerLM(
        vocab_size=48, d_model=16, n_layers=2, n_heads=2, d_ff=32,
        dtype=jnp.float32, **kw,
    )


@pytest.fixture(scope="module")
def model_and_params():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def offline_greedy(model, params, prompt, max_new):
    out = generate(
        model, params, jnp.asarray([prompt], jnp.int32),
        max_new_tokens=max_new, temperature=0.0, rng=jax.random.PRNGKey(0),
    )
    return np.asarray(out)[0, len(prompt):].tolist()


# ---------------------------------------------------------------- allocator


class TestPagedBlockAllocator:
    def test_null_page_reserved(self):
        alloc = PagedBlockAllocator(4)
        pages = alloc.allocate(3)
        assert NULL_PAGE not in pages
        assert sorted(pages) == [1, 2, 3]

    def test_all_or_nothing(self):
        alloc = PagedBlockAllocator(4)
        alloc.allocate(2)
        with pytest.raises(OutOfPages):
            alloc.allocate(2)
        # the failed call took nothing
        assert alloc.num_free == 1
        alloc.check_invariants()

    def test_double_free_detected(self):
        alloc = PagedBlockAllocator(4)
        pages = alloc.allocate(1)
        alloc.free(pages)
        with pytest.raises(AssertionError):
            alloc.free(pages)

    def test_block_table_grow_and_release(self):
        alloc = PagedBlockAllocator(8)
        table = BlockTable()
        assert table.ensure(5, 2, alloc) == 3  # ceil(5/2)
        assert table.ensure(6, 2, alloc) == 0  # already covered
        assert table.ensure(7, 2, alloc) == 1
        row = table.as_row(6)
        assert row.dtype == np.int32
        assert list(row[4:]) == [NULL_PAGE, NULL_PAGE]
        assert table.release(alloc) == 4
        alloc.check_invariants()
        assert alloc.num_free == 7


# ---------------------------------------------------------- scheduler props


class TestSchedulerInvariants:
    def _drive(self, sched, plan):
        """Simulate the device side of one plan: complete every prefill
        chunk, then emit an arbitrary token for every decode slot."""
        finished = []
        for slot, chunk in plan.prefill:
            sched.note_prefilled(slot, chunk)
        for slot in plan.decode_slots:
            done = sched.note_decoded(slot, token=1, now=0.0)
            if done is not None:
                sched.retire(done, now=0.0)
                finished.append(done)
        return finished

    def test_no_block_leaked_over_randomized_cycles(self):
        """1k randomized submit/step cycles against a small pool: allocator
        invariants hold at every step and every page is free at the end."""
        rng = random.Random(1234)
        alloc = PagedBlockAllocator(17)
        sched = Scheduler(
            alloc, max_slots=4, page_size=2, pages_per_seq=8,
            token_budget=8, max_prefill_chunk=4,
        )
        next_id = 0
        live = {}
        for cycle in range(1000):
            if rng.random() < 0.4 and len(live) < 32:
                prompt = [rng.randrange(48) for _ in range(rng.randint(1, 9))]
                req = Request(
                    req_id=next_id, prompt=prompt,
                    params=SamplingParams(
                        max_new_tokens=rng.randint(1, 16 - len(prompt))
                    ),
                )
                live[next_id] = req
                sched.add(req)
                next_id += 1
            plan = self._drive(sched, sched.schedule())
            for req in plan:
                del live[req.req_id]
            alloc.check_invariants()
            for req in live.values():
                # every live table is page-aligned with what's cached
                assert len(req.table) >= PagedBlockAllocator.pages_needed(
                    req.len_cached, 2
                )
        # drain whatever is left
        for _ in range(2000):
            if not sched.has_work:
                break
            for req in self._drive(sched, sched.schedule()):
                del live[req.req_id]
        assert not sched.has_work
        assert not live
        alloc.check_invariants()
        assert alloc.num_free == 16  # every allocatable page returned

    def test_preemption_only_evicts_lower_priority(self):
        """With a pool that fits one sequence, the oldest request finishes
        first — newer ones get preempted, never the oldest."""
        alloc = PagedBlockAllocator(5)  # 4 usable pages
        sched = Scheduler(
            alloc, max_slots=2, page_size=2, pages_per_seq=4,
            token_budget=8, max_prefill_chunk=4,
        )
        reqs = [
            Request(req_id=i, prompt=[1, 2, 3],
                    params=SamplingParams(max_new_tokens=5))
            for i in range(2)
        ]
        for r in reqs:
            sched.add(r)
        order = []
        for _ in range(200):
            if not sched.has_work:
                break
            order.extend(
                r.req_id
                for r in TestSchedulerInvariants._drive(self, sched,
                                                        sched.schedule())
            )
        assert order and order[0] == 0, "oldest request must finish first"
        assert reqs[0].preempt_count == 0, (
            "highest-priority request must never be preempted"
        )
        assert reqs[1].preempt_count > 0
        alloc.check_invariants()
        assert alloc.num_free == 4


# ------------------------------------------------------------- engine parity


class TestEngineParity:
    PROMPTS = [[5, 7, 11, 2, 9, 3], [1, 4, 8], [2, 2, 3, 17, 40], [6, 1, 9, 9]]

    def test_continuous_batching_matches_offline_generate(
        self, model_and_params
    ):
        """Greedy continuous batching — including requests submitted
        mid-flight — is token-identical to each prompt decoded alone with
        offline generate()."""
        model, params = model_and_params
        refs = [
            offline_greedy(model, params, p, 14 - len(p))
            for p in self.PROMPTS
        ]
        eng = InferenceEngine(
            model, params, max_slots=4, max_seq_len=64, page_size=4,
            token_budget=16, max_prefill_chunk=8,
        )
        ids = [
            eng.submit(p, SamplingParams(max_new_tokens=14 - len(p)))
            for p in self.PROMPTS[:2]
        ]
        for _ in range(3):
            eng.step()  # the late submissions join a half-drained batch
        ids += [
            eng.submit(p, SamplingParams(max_new_tokens=14 - len(p)))
            for p in self.PROMPTS[2:]
        ]
        eng.run()
        for rid, ref in zip(ids, refs):
            assert eng.poll(rid).generated == ref
        stats = eng.stats()
        assert stats["requests_completed"] == 4
        assert stats["pages_allocated"] == 0

    def test_preempted_sequence_reproduces_identical_tokens(
        self, model_and_params
    ):
        """A pool too small for all requests forces preemption; resumed
        sequences still emit exactly the offline token stream."""
        model, params = model_and_params
        prompts = self.PROMPTS[:3]
        refs = [offline_greedy(model, params, p, 8) for p in prompts]
        eng = InferenceEngine(
            model, params, max_slots=3, max_seq_len=16, page_size=2,
            num_pages=10, token_budget=8, max_prefill_chunk=4,
        )
        ids = [
            eng.submit(p, SamplingParams(max_new_tokens=8)) for p in prompts
        ]
        eng.run()
        assert eng.stats()["preemptions"] > 0, (
            "pool was sized to force preemption"
        )
        assert any(eng.poll(r).preempt_count > 0 for r in ids)
        for rid, ref in zip(ids, refs):
            assert eng.poll(rid).generated == ref
        eng.allocator.check_invariants()
        assert eng.allocator.num_free == 9

    def test_sampled_stream_independent_of_batch_composition(
        self, model_and_params
    ):
        """fold_in(seed, token_index) keys: the same request samples the
        same tokens whether it runs alone or beside other requests."""
        model, params = model_and_params
        sp = SamplingParams(max_new_tokens=10, temperature=1.0, seed=42)
        eng = InferenceEngine(model, params, max_slots=2, max_seq_len=32,
                              page_size=4)
        solo = eng.submit([5, 7, 11], sp)
        eng.run()
        eng2 = InferenceEngine(model, params, max_slots=2, max_seq_len=32,
                               page_size=4)
        eng2.submit(
            [1, 2, 3, 4],
            SamplingParams(max_new_tokens=6, temperature=0.7, seed=7),
        )
        both = eng2.submit([5, 7, 11], sp)
        eng2.run()
        assert eng.poll(solo).generated == eng2.poll(both).generated

    def test_stop_token_ends_request_early(self, model_and_params):
        model, params = model_and_params
        ref = offline_greedy(model, params, [6, 1, 9, 9], 8)
        stop = ref[2]
        assert stop not in ref[:2], "test needs a stop token unique so far"
        eng = InferenceEngine(model, params, max_slots=2, max_seq_len=32,
                              page_size=4)
        rid = eng.submit(
            [6, 1, 9, 9],
            SamplingParams(max_new_tokens=8, stop_token=stop),
        )
        eng.run()
        assert eng.poll(rid).generated == ref[:3]  # stop token included


# --------------------------------------------------------------- admission


class TestAdmission:
    def test_queue_full_backpressure(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngine(
            model, params, max_slots=1, max_seq_len=16, page_size=4,
            max_queue=2,
        )
        eng.submit([1, 2], SamplingParams(max_new_tokens=2))
        eng.submit([3, 4], SamplingParams(max_new_tokens=2))
        with pytest.raises(QueueFull):
            eng.submit([5, 6], SamplingParams(max_new_tokens=2))
        eng.run()  # queue drains; admission reopens
        rid = eng.submit([5, 6], SamplingParams(max_new_tokens=2))
        eng.run()
        assert eng.poll(rid).finished
        assert eng.stats()["rejected_queue_full"] == 1

    def test_request_too_long_rejected_up_front(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngine(model, params, max_slots=1, max_seq_len=16,
                              page_size=4)
        with pytest.raises(RequestTooLong):
            eng.submit(list(range(12)), SamplingParams(max_new_tokens=8))

    def test_empty_prompt_rejected(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngine(model, params, max_slots=1, max_seq_len=16,
                              page_size=4)
        with pytest.raises(RequestTooLong):
            eng.submit([], SamplingParams(max_new_tokens=2))

    def test_latency_metrics_populated(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngine(model, params, max_slots=2, max_seq_len=32,
                              page_size=4)
        for p in ([1, 2, 3], [4, 5]):
            eng.submit(p, SamplingParams(max_new_tokens=4))
        eng.run()
        s = eng.stats()
        assert s["ttft_s_count"] == 2
        assert s["e2e_s_count"] == 2
        assert s["tpot_s_count"] == 2
        assert s["ttft_s_p50"] > 0
        assert s["tokens_generated"] == 8
