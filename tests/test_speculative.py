"""Speculative decoding: the acceptance rule must make the output EXACTLY
the target model's own decode — token-for-token greedy at temperature 0,
exactly target-distributed rejection sampling above it. Speedup may vary
with the draft, correctness may not."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.generation import generate
from distributed_pytorch_tpu.models import TransformerLM
from distributed_pytorch_tpu.speculative import speculative_generate

V = 48


def lm(seed=1, **kw):
    cfg = dict(vocab_size=V, d_model=16, n_layers=2, n_heads=2, d_ff=32,
               dtype=jnp.float32)
    cfg.update(kw)
    return TransformerLM(**cfg)


def init(model, batch=2, seq=8, seed=0, key=1):
    # seq=8 is a power of two: the prefill buckets to exactly the prompt
    # length, so no round replays prompt tail positions and the acceptance
    # stats (which count generated positions only) stay exact.
    tokens = np.random.default_rng(seed).integers(0, V, (batch, seq), np.int32)
    params = model.init(jax.random.PRNGKey(key), jnp.asarray(tokens))["params"]
    return params, tokens


class TestExactGreedyParity:
    def test_draft_equals_target_accepts_everything(self):
        """A perfect draft (the target itself) must accept every chunk:
        positions_advanced == rounds * gamma, and the tokens are the plain
        greedy decode."""
        model = lm()
        params, tokens = init(model)
        ref = np.asarray(generate(model, params, jnp.asarray(tokens), 12))
        out, stats = speculative_generate(
            model, params, model, params, jnp.asarray(tokens), 12,
            gamma=4, return_stats=True,
        )
        np.testing.assert_array_equal(np.asarray(out), ref)
        assert int(stats["positions_advanced"]) == 4 * int(stats["rounds"])

    def test_independent_draft_still_exact(self):
        """A differently-initialized (i.e. bad) draft changes only the
        round count, never the output."""
        model = lm()
        params, tokens = init(model)
        draft = lm()
        draft_params, _ = init(draft, key=99)
        ref = np.asarray(generate(model, params, jnp.asarray(tokens), 12))
        out, stats = speculative_generate(
            model, params, draft, draft_params, jnp.asarray(tokens), 12,
            gamma=4, return_stats=True,
        )
        np.testing.assert_array_equal(np.asarray(out), ref)
        # STRICTLY more rounds than the perfect draft needs — a regression
        # that silently accepts everything (e.g. comparing the draft to
        # itself) would pass a >= bound, not this.
        _, perfect = speculative_generate(
            model, params, model, params, jnp.asarray(tokens), 12,
            gamma=4, return_stats=True,
        )
        assert int(stats["rounds"]) > int(perfect["rounds"]), (
            stats, perfect,
        )

    def test_narrow_draft_architecture(self):
        """The realistic shape: a narrower, shallower draft sharing only
        the vocabulary."""
        model = lm()
        params, tokens = init(model, batch=3, seq=8)
        draft = lm(d_model=8, n_layers=1, n_heads=1, d_ff=16)
        draft_params, _ = init(draft, key=7)
        ref = np.asarray(generate(model, params, jnp.asarray(tokens), 9))
        out = speculative_generate(
            model, params, draft, draft_params, jnp.asarray(tokens), 9,
            gamma=3,
        )
        np.testing.assert_array_equal(np.asarray(out), ref)

    @pytest.mark.parametrize("gamma", [1, 2, 5])
    def test_gamma_sweep(self, gamma):
        model = lm()
        params, tokens = init(model)
        draft = lm(d_model=8, n_layers=1, n_heads=1, d_ff=16)
        draft_params, _ = init(draft, key=3)
        ref = np.asarray(generate(model, params, jnp.asarray(tokens), 7))
        out = speculative_generate(
            model, params, draft, draft_params, jnp.asarray(tokens), 7,
            gamma=gamma,
        )
        np.testing.assert_array_equal(np.asarray(out), ref)

    def test_ragged_prompts(self):
        """Rows with different prompt lengths: prompt positions are given,
        not generated — they must be auto-accepted and preserved verbatim,
        and the continuations must match plain greedy decode."""
        model = lm()
        params, tokens = init(model, batch=3, seq=9)
        lengths = jnp.asarray([9, 5, 7], jnp.int32)
        t = jnp.asarray(tokens)
        ref = np.asarray(
            generate(model, params, t, 8, prompt_lengths=lengths)
        )
        draft = lm(d_model=8, n_layers=1, n_heads=1, d_ff=16)
        draft_params, _ = init(draft, key=5)
        out = np.asarray(
            speculative_generate(
                model, params, draft, draft_params, t, 8,
                prompt_lengths=lengths, gamma=4,
            )
        )
        np.testing.assert_array_equal(out, ref)
        for row, L in enumerate([9, 5, 7]):
            np.testing.assert_array_equal(out[row, :L], tokens[row, :L])


class TestSampledSpeculative:
    """temperature > 0: modified rejection sampling. The lemma says every
    emitted token is exactly p-distributed; we pin the perfect-draft
    invariant deterministically and the marginal law statistically."""

    def test_perfect_draft_accepts_every_sample(self):
        """draft == target => p == q => acceptance probability 1 at every
        position (u < 1 a.s.), so advance == gamma * rounds exactly."""
        model = lm()
        params, tokens = init(model)
        out, stats = speculative_generate(
            model, params, model, params, jnp.asarray(tokens), 12,
            gamma=4, temperature=0.8, top_k=8,
            rng=jax.random.PRNGKey(3), return_stats=True,
        )
        assert out.shape == (2, 20)
        assert int(stats["positions_advanced"]) == 4 * int(stats["rounds"])

    def test_deterministic_given_rng_and_prompt_preserved(self):
        model = lm()
        params, tokens = init(model, batch=3)
        draft = lm(d_model=8, n_layers=1, n_heads=1, d_ff=16)
        draft_params, _ = init(draft, key=7)
        kw = dict(gamma=3, temperature=1.0, rng=jax.random.PRNGKey(5))
        a = np.asarray(speculative_generate(
            model, params, draft, draft_params, jnp.asarray(tokens), 10, **kw
        ))
        b = np.asarray(speculative_generate(
            model, params, draft, draft_params, jnp.asarray(tokens), 10, **kw
        ))
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a[:, :8], tokens)
        c = np.asarray(speculative_generate(
            model, params, draft, draft_params, jnp.asarray(tokens), 10,
            gamma=3, temperature=1.0, rng=jax.random.PRNGKey(6),
        ))
        assert not np.array_equal(a, c)

    def test_marginal_law_matches_target_distribution(self):
        """The exactness lemma, measured: 2048 independent rows decode ONE
        sampled token through a bad draft; the empirical histogram must
        match the target's softmax at the prompt's last position (and a
        plain-sampling control run must pass the same tolerance, so the
        bound is calibrated, not vacuous)."""
        model = lm()
        params, _ = init(model)
        draft = lm(d_model=8, n_layers=1, n_heads=1, d_ff=16)
        draft_params, _ = init(draft, key=13)
        B = 2048
        prompt = np.tile(
            np.random.default_rng(0).integers(0, V, (1, 8), np.int32),
            (B, 1),
        )
        temp = 1.0
        out = np.asarray(speculative_generate(
            model, params, draft, draft_params, jnp.asarray(prompt), 1,
            gamma=2, temperature=temp, rng=jax.random.PRNGKey(11),
        ))[:, -1]
        logits = model.apply({"params": params}, jnp.asarray(prompt[:1]))
        p = np.asarray(jax.nn.softmax(logits[0, -1] / temp)).astype(np.float64)
        p = p / p.sum()
        hist = np.bincount(out, minlength=V) / B
        tv_spec = 0.5 * np.abs(hist - p).sum()
        control = np.asarray(generate(
            model, params, jnp.asarray(prompt), 1, temperature=temp,
            rng=jax.random.PRNGKey(12),
        ))[:, -1]
        tv_plain = 0.5 * np.abs(
            np.bincount(control, minlength=V) / B - p
        ).sum()
        # Expected TV of a 2048-sample empirical law on ~48 categories is
        # ~0.08; 0.15 rejects any systematically wrong distribution while
        # the control pins the tolerance as fair.
        assert tv_spec < 0.15, (tv_spec, tv_plain)
        assert tv_plain < 0.15, tv_plain

    def test_ragged_prompts_sampled(self):
        model = lm()
        params, tokens = init(model, batch=3, seq=9)
        lengths = jnp.asarray([9, 5, 7], jnp.int32)
        draft = lm(d_model=8, n_layers=1, n_heads=1, d_ff=16)
        draft_params, _ = init(draft, key=5)
        out = np.asarray(speculative_generate(
            model, params, draft, draft_params, jnp.asarray(tokens), 8,
            prompt_lengths=lengths, gamma=4, temperature=0.7,
            rng=jax.random.PRNGKey(2),
        ))
        for row, L in enumerate([9, 5, 7]):
            np.testing.assert_array_equal(out[row, :L], tokens[row, :L])


class TestMeshSharded:
    def test_dp_mesh_output_matches_single_device(self):
        """Batch-sharded speculative decode (tokens + both caches
        P('data'), params replicated) must be token-for-token identical
        to the unsharded run — greedy and sampled."""
        from distributed_pytorch_tpu.parallel.mesh import make_mesh

        model = lm()
        params, tokens = init(model, batch=8)
        draft = lm(d_model=8, n_layers=1, n_heads=1, d_ff=16)
        draft_params, _ = init(draft, key=7)
        mesh = make_mesh()
        for kw in (
            dict(gamma=3),
            dict(gamma=3, temperature=0.8, rng=jax.random.PRNGKey(4)),
        ):
            ref, ref_stats = speculative_generate(
                model, params, draft, draft_params, jnp.asarray(tokens), 9,
                return_stats=True, **kw,
            )
            out, stats = speculative_generate(
                model, params, draft, draft_params, jnp.asarray(tokens), 9,
                mesh=mesh, return_stats=True, **kw,
            )
            np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
            assert int(stats["rounds"]) == int(ref_stats["rounds"])


class TestValidation:
    def test_vocab_mismatch_rejected(self):
        model = lm()
        params, tokens = init(model)
        draft = TransformerLM(
            vocab_size=V + 1, d_model=8, n_layers=1, n_heads=1, d_ff=16,
            dtype=jnp.float32,
        )
        draft_params = draft.init(
            jax.random.PRNGKey(0), jnp.zeros((2, 4), jnp.int32)
        )["params"]
        with np.testing.assert_raises(ValueError):
            speculative_generate(
                model, params, draft, draft_params, jnp.asarray(tokens), 4
            )

    def test_gamma_must_be_positive(self):
        model = lm()
        params, tokens = init(model)
        with np.testing.assert_raises(ValueError):
            speculative_generate(
                model, params, model, params, jnp.asarray(tokens), 4, gamma=0
            )


class TestPerRowAcceptance:
    def test_batched_rounds_equal_slowest_solo_row(self):
        """Acceptance is PER ROW: a batched run needs exactly as many
        rounds as its slowest row needed alone. Under the old
        minimum-across-rows rewind, one bad row dragged every row back and
        the batched count exceeded the solo max."""
        model = lm()
        draft = lm(d_model=8, n_layers=1, n_heads=1, d_ff=16)
        draft_params, _ = init(draft, key=11)
        params, tokens = init(model, batch=4)
        new = 12
        solo_rounds = []
        for b in range(tokens.shape[0]):
            out_b, stats_b = speculative_generate(
                model, params, draft, draft_params,
                jnp.asarray(tokens[b : b + 1]), new, gamma=4,
                return_stats=True,
            )
            solo_rounds.append(int(stats_b["rounds"]))
        out, stats = speculative_generate(
            model, params, draft, draft_params, jnp.asarray(tokens), new,
            gamma=4, return_stats=True,
        )
        assert solo_rounds.count(solo_rounds[0]) < len(solo_rounds), (
            "fixture rows all advance in lockstep — pick a worse draft"
        )
        assert int(stats["rounds"]) == max(solo_rounds)
        # And batching never changes any row's tokens (greedy exactness).
        ref = np.asarray(generate(model, params, jnp.asarray(tokens), new))
        np.testing.assert_array_equal(np.asarray(out), ref)


class TestStats:
    def test_advance_counts_cover_emitted_tokens(self):
        """rounds >= ceil(new/gamma); positions_advanced >= the emitted
        continuation (the final round may advance past total_len)."""
        model = lm()
        params, tokens = init(model)
        draft = lm(d_model=8, n_layers=1, n_heads=1, d_ff=16)
        draft_params, _ = init(draft, key=11)
        new = 10
        out, stats = speculative_generate(
            model, params, draft, draft_params, jnp.asarray(tokens), new,
            gamma=4, return_stats=True,
        )
        rounds = int(stats["rounds"])
        advanced = int(stats["positions_advanced"])
        assert out.shape[-1] == tokens.shape[1] + new
        assert advanced >= new - 1  # t0 may start 1 short of prompt end
        assert rounds <= advanced  # every round advances >= 1
        assert advanced <= rounds * 4
