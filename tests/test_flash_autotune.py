"""Flash block-size selection: candidate legality and lookup tiers (the
measured sweep itself needs real hardware; its results ship in
DEFAULT_TABLE — see BASELINE.md)."""

import json

import pytest

from distributed_pytorch_tpu.ops import flash_autotune as fa


@pytest.fixture(autouse=True)
def _isolated_caches(tmp_path, monkeypatch):
    """Every test sees an empty disk cache (a dev box where a real sweep ran
    must not leak measured winners in) and a clean in-process cache."""
    monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path))
    monkeypatch.setattr(fa, "_runtime_cache", {})


def test_candidates_are_legal():
    for t in (2048, 8192, 16384):
        for d in (64, 128):
            cands = list(fa.candidates(t, d))
            assert cands, (t, d)
            for bq, bk in cands:
                assert t % bq == 0 and t % bk == 0
                assert bk % 128 == 0  # lane alignment
                # VMEM bound honored
                assert bq * bk * 4 + 2 * bk * d * 4 <= 12 * 2**20


def test_lookup_uses_shipped_table_nearest_bucket():
    # Exact bucket.
    assert fa.lookup(16384, 64, device_kind="TPU v5 lite") == (1024, 1024)
    # Nearest bucket: T=12288 sits nearer 16384 than 8192... check stability
    # for an off-table T and d.
    blocks = fa.lookup(4096, 96, device_kind="TPU v5 lite")
    assert blocks in set(fa.DEFAULT_TABLE["tpu v5 lite"].values())


def test_lookup_on_unknown_device_uses_analytic_default():
    # Round-3 VERDICT: unknown chips were pinned to the bare (512, 1024)
    # guess; now they get the VMEM-reasoned largest legal tile.
    blocks = fa.lookup(8192, 64, device_kind="TPU v99")
    assert blocks == fa.analytic_default(8192, 64)
    assert blocks in set(fa.candidates(8192, 64))


def test_analytic_default_legality_and_preference():
    for t in (2048, 4096, 8192, 16384, 32768):
        for d in (64, 128, 256):
            bq, bk = fa.analytic_default(t, d)
            assert t % bq == 0 and t % bk == 0, (t, d)
            assert bq * bk * 4 + 2 * bk * d * 4 <= 12 * 2**20, (t, d)
    # At long T / d=64 every large candidate is legal: picks the largest
    # area, square-preferred — matching the measured v5e winner.
    assert fa.analytic_default(16384, 64) == (1024, 1024)
    # Odd T with no standard divisor degrades to the legacy fallback.
    assert fa.analytic_default(1000, 64) == fa._FALLBACK


def test_disk_cache_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path))
    fa._save_disk_cache({("tpu v5 lite", 1024, 64, "bfloat16", True): (256, 512)})
    got = fa._load_disk_cache()
    assert got[("tpu v5 lite", 1024, 64, "bfloat16", True)] == (256, 512)
    # Cache file is valid JSON on disk.
    with open(fa._cache_path()) as f:
        json.load(f)


def test_runtime_cache_wins_over_table(monkeypatch):
    key = fa._key("TPU v5 lite", 16384, 64, "bfloat16", True)
    monkeypatch.setitem(fa._runtime_cache, key, (256, 256))
    assert fa.lookup(16384, 64, device_kind="TPU v5 lite") == (256, 256)


class TestShippedTableFile:
    """FLASH_BLOCKS_TABLE: the pod workflow — an exported table outranks the
    host's private disk cache, so all hosts pick identical blocks."""

    def test_explicit_table_wins(self, tmp_path, monkeypatch):
        import json

        from distributed_pytorch_tpu.ops import flash_autotune as fa

        key = fa._key("tpu v99", 4096, 64, "bfloat16", True)
        table = tmp_path / "blocks.json"
        table.write_text(json.dumps({json.dumps(list(key)): [256, 512]}))
        monkeypatch.setenv("FLASH_BLOCKS_TABLE", str(table))
        monkeypatch.setattr(fa, "_runtime_cache", {})
        fa._load_table_file.cache_clear()
        assert fa.lookup(4096, 64, "bfloat16", True, device_kind="tpu v99") == (
            256,
            512,
        )

    def test_missing_table_fails_loudly(self, tmp_path, monkeypatch):
        import pytest

        from distributed_pytorch_tpu.ops import flash_autotune as fa

        monkeypatch.setenv("FLASH_BLOCKS_TABLE", str(tmp_path / "absent.json"))
        monkeypatch.setattr(fa, "_runtime_cache", {})
        fa._load_table_file.cache_clear()
        with pytest.raises(FileNotFoundError):
            fa.lookup(4096, 64, "bfloat16", True, device_kind="tpu v99")

    def test_shape_not_in_table_falls_through(self, tmp_path, monkeypatch):
        import json

        from distributed_pytorch_tpu.ops import flash_autotune as fa

        table = tmp_path / "blocks.json"
        table.write_text(json.dumps({}))
        monkeypatch.setenv("FLASH_BLOCKS_TABLE", str(table))
        monkeypatch.setattr(fa, "_runtime_cache", {})
        fa._load_table_file.cache_clear()
        # Unknown device, empty table -> analytic VMEM-reasoned default.
        assert fa.lookup(
            4096, 64, "bfloat16", True, device_kind="tpu v99"
        ) == fa.analytic_default(4096, 64)
