"""Tensor-parallel / FSDP partitioning tests on the 8-device CPU mesh.

Verifies that sharded-state training (a) places parameters and Adam moments
according to the rules, and (b) produces the SAME numbers as replicated
data-parallel training — the sharding is a placement annotation, not a
semantic change (SURVEY.md §2b: TP/FSDP are beyond-parity capabilities).
"""

import jax
import jax.tree_util as jtu
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from distributed_pytorch_tpu.models import TransformerLM
from distributed_pytorch_tpu.parallel.mesh import make_mesh
from distributed_pytorch_tpu.parallel.partitioning import (
    TRANSFORMER_TP_RULES,
    make_fsdp_specs,
    make_param_specs,
    make_state_shardings,
    make_state_specs,
    make_zero1_shardings,
    make_zero1_state_specs,
    shard_train_state,
)
from distributed_pytorch_tpu.parallel.sharding import (
    put_global_batch,
    replicated_sharding,
)
from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss
from distributed_pytorch_tpu.training.train_step import (
    create_train_state,
    make_train_step,
)


def tiny_lm(**kw):
    return TransformerLM(
        vocab_size=64, d_model=16, n_layers=2, n_heads=4, d_ff=32, **kw
    )


def make_batch(dp=1):
    rng = np.random.default_rng(7)
    tokens = rng.integers(0, 64, (4 * dp, 17), dtype=np.int32)
    return tokens[:, :-1], tokens[:, 1:]


def test_tp_rules_assign_expected_specs():
    model = tiny_lm()
    inputs, _ = make_batch()
    state = create_train_state(model, optax.adam(1e-3), inputs)
    specs = make_param_specs(state.params, TRANSFORMER_TP_RULES)
    flat = {path: spec for path, spec in jtu.tree_flatten_with_path(specs)[0]}

    def spec_of(path_suffix):
        for path, spec in flat.items():
            joined = "/".join(str(getattr(e, "key", e)) for e in path)
            if joined.endswith(path_suffix):
                return spec
        raise KeyError(path_suffix)

    assert spec_of("block_0/attention/query/kernel") == P(None, "tensor", None)
    assert spec_of("block_0/attention/out/kernel") == P("tensor", None, None)
    assert spec_of("block_1/mlp/up/kernel") == P(None, "tensor")
    assert spec_of("block_1/mlp/down/kernel") == P("tensor", None)
    assert spec_of("embed/embedding") == P(None, "tensor")
    assert spec_of("lm_head/kernel") == P(None, "tensor")
    # LayerNorm scales replicate.
    assert spec_of("ln_final/scale") == P()


def test_divisibility_validation_raises():
    mesh = make_mesh({"data": 1, "tensor": 8})
    model = tiny_lm()  # n_heads=4 < tensor=8 -> QKV heads dim not divisible
    inputs, _ = make_batch()
    state = create_train_state(model, optax.adam(1e-3), inputs)
    with pytest.raises(ValueError, match="not.*divisible|divisible"):
        make_param_specs(state.params, TRANSFORMER_TP_RULES, mesh=mesh)


def test_adam_moments_shard_like_params():
    mesh = make_mesh({"data": 2, "tensor": 4})
    model = tiny_lm()
    inputs, _ = make_batch(dp=2)
    state = create_train_state(model, optax.adam(1e-3), inputs)
    specs = make_param_specs(state.params, TRANSFORMER_TP_RULES, mesh=mesh)
    state_specs = make_state_specs(state, specs)
    # ScaleByAdamState(count, mu, nu): mu/nu mirror the param tree.
    adam = state_specs.opt_state[0]
    assert jtu.tree_structure(adam.mu) == jtu.tree_structure(specs)
    leaves_mu = jtu.tree_leaves(adam.mu, is_leaf=lambda x: isinstance(x, P))
    leaves_p = jtu.tree_leaves(specs, is_leaf=lambda x: isinstance(x, P))
    assert leaves_mu == leaves_p
    assert adam.count == P()


@pytest.mark.parametrize("mode", ["tp", "fsdp"])
@pytest.mark.slow
def test_sharded_training_matches_replicated(mode):
    """DP+TP (and DP+FSDP) training must be numerically equivalent to pure-DP
    replicated training: shardings change placement, not math."""
    model = tiny_lm()
    inputs, targets = make_batch(dp=2)
    optimizer = optax.adam(1e-2)

    # Replicated DP reference run.
    mesh_dp = make_mesh({"data": 2}, devices=jax.devices()[:2])
    state = create_train_state(model, optimizer, inputs, rng_seed=3)
    state_dp = shard_train_state(state, replicated_sharding(mesh_dp))
    step_dp = make_train_step(
        model.apply, optimizer, softmax_cross_entropy_loss, mesh=mesh_dp
    )
    losses_dp = []
    batch = put_global_batch(mesh_dp, (inputs, targets))
    for _ in range(3):
        state_dp, loss = step_dp(state_dp, batch)
        losses_dp.append(float(loss))

    # Sharded run on a 2x4 mesh.
    axis = "tensor" if mode == "tp" else "fsdp"
    mesh = make_mesh({"data": 2, axis: 4})
    state2 = create_train_state(model, optimizer, inputs, rng_seed=3)
    if mode == "tp":
        specs = make_param_specs(state2.params, TRANSFORMER_TP_RULES, mesh=mesh)
    else:
        specs = make_fsdp_specs(state2.params, mesh=mesh)
        assert any(
            spec != P()
            for spec in jtu.tree_leaves(specs, is_leaf=lambda x: isinstance(x, P))
        )
    shardings = make_state_shardings(mesh, state2, specs)
    state2 = shard_train_state(state2, shardings)
    step = make_train_step(
        model.apply,
        optimizer,
        softmax_cross_entropy_loss,
        mesh=mesh,
        state_sharding=shardings,
    )
    batch2 = put_global_batch(mesh, (inputs, targets))
    losses = []
    for _ in range(3):
        state2, loss = step(state2, batch2)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, losses_dp, rtol=2e-4)
    # Spot-check a parameter is actually sharded on device.
    sharded_leaves = [
        leaf
        for leaf, spec in zip(
            jtu.tree_leaves(state2.params),
            jtu.tree_leaves(specs, is_leaf=lambda x: isinstance(x, P)),
        )
        if spec != P()
    ]
    assert sharded_leaves
    assert not sharded_leaves[0].sharding.is_fully_replicated


def test_zero1_specs_shard_moments_not_params():
    mesh = make_mesh({"data": 8})
    model = tiny_lm()
    inputs, _ = make_batch(dp=8)
    state = create_train_state(model, optax.adam(1e-3), inputs)
    specs = make_zero1_state_specs(state, mesh=mesh)
    param_leaves = jtu.tree_leaves(
        specs.params, is_leaf=lambda x: isinstance(x, P)
    )
    assert all(spec == P() for spec in param_leaves)
    adam = specs.opt_state[0]  # ScaleByAdamState(count, mu, nu)
    mu_leaves = jtu.tree_leaves(adam.mu, is_leaf=lambda x: isinstance(x, P))
    assert any(spec != P() for spec in mu_leaves)
    assert all(
        axis in (None, "data")
        for spec in mu_leaves
        for axis in spec
    )


@pytest.mark.slow
def test_zero1_training_matches_replicated_dp():
    """ZeRO-1 (sharded Adam moments, replicated params) is pure placement:
    the loss curve must match replicated DP, params must stay replicated on
    device, and the moments must actually be distributed."""
    model = tiny_lm()
    inputs, targets = make_batch(dp=8)
    optimizer = optax.adam(1e-2)
    mesh = make_mesh({"data": 8})
    batch = put_global_batch(mesh, (inputs, targets))

    state_dp = create_train_state(model, optimizer, inputs, rng_seed=3)
    state_dp = shard_train_state(state_dp, replicated_sharding(mesh))
    step_dp = make_train_step(
        model.apply, optimizer, softmax_cross_entropy_loss, mesh=mesh
    )
    losses_dp = []
    for _ in range(3):
        state_dp, loss = step_dp(state_dp, batch)
        losses_dp.append(float(loss))

    state_z = create_train_state(model, optimizer, inputs, rng_seed=3)
    shardings = make_zero1_shardings(mesh, state_z)
    state_z = shard_train_state(state_z, shardings)
    step_z = make_train_step(
        model.apply,
        optimizer,
        softmax_cross_entropy_loss,
        mesh=mesh,
        state_sharding=shardings,
    )
    losses_z = []
    for _ in range(3):
        state_z, loss = step_z(state_z, batch)
        losses_z.append(float(loss))

    np.testing.assert_allclose(losses_z, losses_dp, rtol=2e-4)
    assert all(
        leaf.sharding.is_fully_replicated
        for leaf in jtu.tree_leaves(state_z.params)
    )
    mu_arrays = jtu.tree_leaves(state_z.opt_state[0].mu)
    assert any(not a.sharding.is_fully_replicated for a in mu_arrays)
