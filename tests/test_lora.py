"""LoRA: adapters are the trainable params, the base is frozen state.

The contracts: (1) B-at-zero makes step 0 exactly the base model, (2) a
training run moves ONLY the adapters — the base tree is bit-identical
after training, (3) optimizer state scales with rank x (m + n), not
m x n, (4) the merged export reproduces the wrapped forward."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_pytorch_tpu.models import TransformerLM
from distributed_pytorch_tpu.training.lora import (
    DEFAULT_LORA_RULES,
    LoraModel,
    init_lora,
    merge_lora,
)
from distributed_pytorch_tpu.training.losses import (
    softmax_cross_entropy_loss,
)
from distributed_pytorch_tpu.training.train_step import (
    create_train_state,
    make_train_step,
)

V = 32


def lm(**kw):
    cfg = dict(vocab_size=V, d_model=16, n_layers=2, n_heads=2, d_ff=32,
               dtype=jnp.float32)
    cfg.update(kw)
    return TransformerLM(**cfg)


def tokens(batch=4, seq=8, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).integers(0, V, (batch, seq), np.int32)
    )


def n_elems(tree):
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


class TestInitAndMerge:
    def test_zero_init_is_identity(self):
        """B starts at zero, so merged == base bit-for-bit and the wrapped
        forward equals the plain forward."""
        model = lm()
        t = tokens()
        wrapped = LoraModel(model, rank=4)
        variables = wrapped.init(jax.random.PRNGKey(0), t)
        merged = merge_lora(
            variables["lora_base"], variables["params"], rank=4
        )
        for a, b in zip(
            jax.tree_util.tree_leaves(merged),
            jax.tree_util.tree_leaves(variables["lora_base"]),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        ref = model.apply({"params": variables["lora_base"]}, t)
        out = wrapped.apply(variables, t)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_merge_math_single_leaf(self):
        """W + (alpha/rank) A @ B, checked by hand on the mlp/up kernel."""
        model = lm()
        params = model.init(jax.random.PRNGKey(0), tokens())["params"]
        adapters = init_lora(params, 2, jax.random.PRNGKey(1))
        a = adapters["block_0"]["mlp"]["up"]["kernel"]["lora_a"]
        b = adapters["block_0"]["mlp"]["up"]["kernel"]["lora_b"]
        b = b + 0.3  # make the delta nonzero
        adapters["block_0"]["mlp"]["up"]["kernel"]["lora_b"] = b
        merged = merge_lora(params, adapters, rank=2, alpha=6.0)
        want = params["block_0"]["mlp"]["up"]["kernel"] + 3.0 * (a @ b)
        np.testing.assert_allclose(
            np.asarray(merged["block_0"]["mlp"]["up"]["kernel"]),
            np.asarray(want), rtol=1e-6,
        )

    def test_rules_select_expected_leaves(self):
        """Default rules adapt attention + MLP + head; embeddings, biases,
        and layer norms stay frozen."""
        model = lm()
        params = model.init(jax.random.PRNGKey(0), tokens())["params"]
        adapters = init_lora(params, 2, jax.random.PRNGKey(1))
        from flax import traverse_util

        paths = {
            "/".join(p[:-1])
            for p in traverse_util.flatten_dict(adapters)
        }
        assert "block_0/attention/query/kernel" in paths
        assert "block_1/mlp/down/kernel" in paths
        assert "lm_head/kernel" in paths
        assert not any("embed" in p or "ln_" in p for p in paths)

    def test_3d_attention_kernels_round_trip(self):
        """q/k/v kernels are [in, H, Dh]; the in_first matricization must
        reshape back losslessly — rank-full adapters can represent an
        arbitrary delta on the 3D kernel."""
        model = lm()
        params = model.init(jax.random.PRNGKey(0), tokens())["params"]
        w = params["block_0"]["attention"]["query"]["kernel"]
        m, rest = w.shape[0], int(np.prod(w.shape[1:]))
        rank = min(m, rest)  # full rank: can hit any delta
        adapters = init_lora(params, rank, jax.random.PRNGKey(1))
        delta = jax.random.normal(jax.random.PRNGKey(2), (m, rest))
        adapters["block_0"]["attention"]["query"]["kernel"]["lora_a"] = jnp.eye(m, rank)
        adapters["block_0"]["attention"]["query"]["kernel"]["lora_b"] = delta[:rank]
        merged = merge_lora(params, adapters, rank=rank, alpha=rank)
        got = merged["block_0"]["attention"]["query"]["kernel"] - w
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(delta.reshape(w.shape)), atol=1e-5
        )

    def test_empty_match_rejected(self):
        model = lm()
        params = model.init(jax.random.PRNGKey(0), tokens())["params"]
        with pytest.raises(ValueError, match="no parameter matched"):
            init_lora(
                params, 2, jax.random.PRNGKey(1),
                rules=((r"nothing/matches", "out_last"),),
            )


class TestTraining:
    def test_base_frozen_adapters_move_loss_falls(self):
        """The load-bearing property: training updates ONLY adapters (the
        base tree is bit-identical afterwards) and the loss decreases."""
        model = lm()
        wrapped = LoraModel(model, rank=4)
        t = tokens(batch=8)
        optimizer = optax.adam(1e-2)
        state = create_train_state(wrapped, optimizer, t)
        base0 = jax.tree_util.tree_map(
            np.asarray, state.model_state["lora_base"]
        )
        adapters0 = jax.tree_util.tree_map(np.asarray, state.params)
        step = make_train_step(
            wrapped.apply, optimizer, softmax_cross_entropy_loss
        )
        batch = (t[:, :-1], t[:, 1:])
        losses = []
        for _ in range(12):
            state, loss = step(state, batch)
            losses.append(float(loss))
        # Base bit-identical:
        for a, b in zip(
            jax.tree_util.tree_leaves(state.model_state["lora_base"]),
            jax.tree_util.tree_leaves(base0),
        ):
            np.testing.assert_array_equal(np.asarray(a), b)
        # Adapters moved, loss fell:
        moved = any(
            not np.array_equal(np.asarray(a), b)
            for a, b in zip(
                jax.tree_util.tree_leaves(state.params),
                jax.tree_util.tree_leaves(adapters0),
            )
        )
        assert moved
        assert losses[-1] < losses[0] - 0.1, losses

    def test_optimizer_state_scales_with_adapters(self):
        """Adam moments over adapters only — the memory the distributed
        story cares about (grads/moments/checkpoint-delta all shrink)."""
        model = lm()
        wrapped = LoraModel(model, rank=2)
        t = tokens()
        optimizer = optax.adam(1e-3)
        state = create_train_state(wrapped, optimizer, t)
        full = n_elems(state.model_state["lora_base"])
        adapted = n_elems(state.params)
        opt = n_elems(state.opt_state)
        assert adapted < full / 5
        assert opt <= 2 * adapted + 8  # two moments + step counters

    def test_merged_export_matches_wrapped_forward(self):
        """After training, merged_params(state) fed to the PLAIN model
        reproduces the wrapped forward — the inference-export contract."""
        model = lm()
        wrapped = LoraModel(model, rank=4, alpha=8.0)
        t = tokens(batch=8)
        optimizer = optax.sgd(1e-2)
        state = create_train_state(wrapped, optimizer, t)
        step = make_train_step(
            wrapped.apply, optimizer, softmax_cross_entropy_loss
        )
        batch = (t[:, :-1], t[:, 1:])
        for _ in range(3):
            state, _ = step(state, batch)
        variables = {"params": state.params, **state.model_state}
        ref = wrapped.apply(variables, t)
        out = model.apply({"params": wrapped.merged_params(state)}, t)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-5
        )

    def test_snapshot_resume_bit_exact(self, tmp_path):
        """LoRA composes with the elastic snapshot contract: adapters ride
        in params, the frozen base in model_state — both checkpoint, and a
        resumed run continues bit-identically to an uninterrupted one."""
        from distributed_pytorch_tpu.checkpoint import (
            load_snapshot,
            save_snapshot,
        )

        model = lm()
        wrapped = LoraModel(model, rank=2)
        t = tokens(batch=8)
        batch = (t[:, :-1], t[:, 1:])
        optimizer = optax.adam(1e-2)

        def fresh():
            return create_train_state(wrapped, optimizer, t)

        step = make_train_step(
            wrapped.apply, optimizer, softmax_cross_entropy_loss
        )

        # Uninterrupted: 6 steps.
        state = fresh()
        for _ in range(6):
            state, _ = step(state, batch)
        ref = jax.tree_util.tree_map(np.asarray, state.params)

        # Interrupted at 3, snapshot, restore into a fresh template, resume.
        state = fresh()
        for _ in range(3):
            state, _ = step(state, batch)
        path = str(tmp_path / "lora_snap.npz")
        save_snapshot(path, state, epochs_run=1)
        restored, snap_meta = load_snapshot(path, fresh())
        assert snap_meta["epochs_run"] == 1
        for _ in range(3):
            restored, _ = step(restored, batch)
        for a, b in zip(
            jax.tree_util.tree_leaves(restored.params),
            jax.tree_util.tree_leaves(ref),
        ):
            np.testing.assert_array_equal(np.asarray(a), b)

    def test_dp_mesh_parity_with_serial(self):
        """The distributed contract: the LoRA step under an 8-device data
        mesh reproduces the serial loss curve exactly (same reduction
        semantics as the plain step)."""
        from distributed_pytorch_tpu.parallel.mesh import make_mesh
        from distributed_pytorch_tpu.parallel.sharding import (
            put_global_batch,
            replicated_sharding,
        )

        model = lm()
        wrapped = LoraModel(model, rank=2)
        t = tokens(batch=8)
        batch = (t[:, :-1], t[:, 1:])
        optimizer = optax.sgd(1e-2)

        serial_state = create_train_state(wrapped, optimizer, t)
        serial_step = make_train_step(
            wrapped.apply, optimizer, softmax_cross_entropy_loss
        )
        serial_losses = []
        for _ in range(4):
            serial_state, loss = serial_step(serial_state, batch)
            serial_losses.append(float(loss))

        mesh = make_mesh()
        state = create_train_state(wrapped, optimizer, t)
        state = jax.device_put(state, replicated_sharding(mesh))
        sharded = tuple(put_global_batch(mesh, np.asarray(x)) for x in batch)
        step = make_train_step(
            wrapped.apply, optimizer, softmax_cross_entropy_loss, mesh=mesh
        )
        mesh_losses = []
        for _ in range(4):
            state, loss = step(state, sharded)
            mesh_losses.append(float(loss))
        np.testing.assert_allclose(mesh_losses, serial_losses, rtol=2e-5)
