#!/bin/bash
# Multi-host pod-slice launcher — twin of slurm/sbatch_run.sh in the reference.
#
# Where the reference's SLURM job discovers a head-node IP and launches torchrun
# on every node with a c10d rendezvous endpoint (sbatch_run.sh:9-23), a TPU pod
# slice needs only "run the same command on every worker": each host process
# calls jax.distributed.initialize(), which autodetects the coordinator from
# TPU metadata. No head-node discovery, no rendezvous port, no per-node agent.
#
# Usage:
#   TPU_NAME=my-v4-32 ZONE=us-central2-b ./launch/tpu_pod_run.sh 50 5
#
# Prereqs: the repo cloned at the same path on every worker (see
# launch/setup_tpu_pod.md), gcloud authenticated.

set -euo pipefail

TPU_NAME="${TPU_NAME:?set TPU_NAME to your TPU VM/slice name}"
ZONE="${ZONE:?set ZONE to the TPU's GCP zone}"
REPO_DIR="${REPO_DIR:-\$HOME/distributed_pytorch_tpu}"
TOTAL_EPOCHS="${1:-50}"
SAVE_EVERY="${2:-5}"

gcloud compute tpus tpu-vm ssh "$TPU_NAME" \
  --zone="$ZONE" \
  --worker=all \
  --command="cd $REPO_DIR && pip install -q -e . && python examples/multihost_pod.py $TOTAL_EPOCHS $SAVE_EVERY"
